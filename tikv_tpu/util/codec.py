"""Low-level encodings: memcomparable bytes, fixed/var ints, f64.

TPU-native re-expression of the reference's codec crate
(``components/codec/src/byte.rs``, ``number.rs``): same wire formats (so keys
sort identically and datum payloads round-trip), but implemented once as Python
scalar codecs and once as numpy batch codecs — the batch variants are what the
coprocessor leaf uses to turn row blocks into columnar arrays without a Python
loop per row.

Wire formats (identical to the reference):

* memcomparable bytes (asc): the input is chopped into groups of 8; every group
  is zero-padded to 8 bytes and followed by a marker byte ``0xFF - pad_count``.
  Descending variant bit-flips every byte of the ascending encoding.
* u64: 8-byte big-endian.  i64: sign bit flipped, then as u64.
* f64: if sign bit clear, flip sign bit; else flip all 64 bits; then big-endian.
* varint: LEB128 (u64); signed variant uses zigzag.
* compact bytes: zigzag varint length prefix + raw bytes.
"""

from __future__ import annotations

import struct

import numpy as np

ENC_GROUP_SIZE = 8
ENC_MARKER = 0xFF
ENC_ASC_PADDING = b"\x00" * ENC_GROUP_SIZE
ENC_DESC_PADDING = b"\xff" * ENC_GROUP_SIZE

_U64 = struct.Struct(">Q")
_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U64_LE = struct.Struct("<Q")
_U32 = struct.Struct(">I")
_U16 = struct.Struct(">H")

SIGN_MASK = 0x8000000000000000


# ---------------------------------------------------------------------------
# memcomparable bytes
# ---------------------------------------------------------------------------

def encode_bytes(data: bytes, desc: bool = False) -> bytes:
    """Encode ``data`` so lexicographic compare of encodings == compare of data."""
    out = bytearray()
    n = len(data)
    for i in range(0, n + 1, ENC_GROUP_SIZE):
        group = data[i : i + ENC_GROUP_SIZE]
        pad = ENC_GROUP_SIZE - len(group)
        out += group
        out += ENC_ASC_PADDING[:pad]
        out.append(ENC_MARKER - pad)
        if pad > 0:
            break
    if desc:
        return bytes(b ^ 0xFF for b in out)
    return bytes(out)


def decode_bytes(enc: bytes, desc: bool = False) -> tuple[bytes, int]:
    """Decode memcomparable bytes. Returns (data, bytes_consumed)."""
    out = bytearray()
    offset = 0
    xor = 0xFF if desc else 0x00
    while True:
        chunk = enc[offset : offset + ENC_GROUP_SIZE + 1]
        if len(chunk) < ENC_GROUP_SIZE + 1:
            raise ValueError("insufficient bytes to decode")
        marker = chunk[ENC_GROUP_SIZE] ^ xor
        pad = ENC_MARKER - marker
        if not 0 <= pad <= ENC_GROUP_SIZE:
            raise ValueError(f"invalid marker byte {marker:#x}")
        group = bytes(b ^ xor for b in chunk[:ENC_GROUP_SIZE])
        offset += ENC_GROUP_SIZE + 1
        if pad:
            padding = group[ENC_GROUP_SIZE - pad :]
            expect = b"\x00" * pad
            if padding != expect:
                raise ValueError("invalid padding")
            out += group[: ENC_GROUP_SIZE - pad]
            return bytes(out), offset
        out += group


def encoded_bytes_len(enc: bytes, desc: bool = False) -> int:
    """Length of the memcomparable run at the head of ``enc``."""
    xor = 0xFF if desc else 0x00
    offset = 0
    while True:
        if offset + ENC_GROUP_SIZE >= len(enc):
            raise ValueError("insufficient bytes")
        marker = enc[offset + ENC_GROUP_SIZE] ^ xor
        offset += ENC_GROUP_SIZE + 1
        if marker != ENC_MARKER:
            return offset


# ---------------------------------------------------------------------------
# fixed-width numbers
# ---------------------------------------------------------------------------

def encode_u64(v: int) -> bytes:
    return _U64.pack(v & 0xFFFFFFFFFFFFFFFF)


def decode_u64(b: bytes, offset: int = 0) -> int:
    return _U64.unpack_from(b, offset)[0]


def encode_u64_desc(v: int) -> bytes:
    return _U64.pack((v & 0xFFFFFFFFFFFFFFFF) ^ 0xFFFFFFFFFFFFFFFF)


def decode_u64_desc(b: bytes, offset: int = 0) -> int:
    return _U64.unpack_from(b, offset)[0] ^ 0xFFFFFFFFFFFFFFFF


def encode_u64_le(v: int) -> bytes:
    return _U64_LE.pack(v & 0xFFFFFFFFFFFFFFFF)


def decode_u64_le(b: bytes, offset: int = 0) -> int:
    return _U64_LE.unpack_from(b, offset)[0]


def encode_i64(v: int) -> bytes:
    return _U64.pack((v ^ SIGN_MASK) & 0xFFFFFFFFFFFFFFFF)


def decode_i64(b: bytes, offset: int = 0) -> int:
    u = _U64.unpack_from(b, offset)[0] ^ SIGN_MASK
    return u - 0x10000000000000000 if u & SIGN_MASK else u


def encode_f64(v: float) -> bytes:
    (u,) = _U64.unpack(_F64.pack(v))
    if u & SIGN_MASK:
        u ^= 0xFFFFFFFFFFFFFFFF
    else:
        u ^= SIGN_MASK
    return _U64.pack(u)


def decode_f64(b: bytes, offset: int = 0) -> float:
    u = _U64.unpack_from(b, offset)[0]
    if u & SIGN_MASK:
        u ^= SIGN_MASK
    else:
        u ^= 0xFFFFFFFFFFFFFFFF
    return _F64.unpack(_U64.pack(u))[0]


# ---------------------------------------------------------------------------
# varints
# ---------------------------------------------------------------------------

def encode_var_u64(v: int) -> bytes:
    out = bytearray()
    v &= 0xFFFFFFFFFFFFFFFF
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def decode_var_u64(b: bytes, offset: int = 0) -> tuple[int, int]:
    """Returns (value, new_offset)."""
    result = 0
    shift = 0
    while True:
        if offset >= len(b):
            raise ValueError("varint truncated")
        byte = b[offset]
        offset += 1
        result |= (byte & 0x7F) << shift
        if byte < 0x80:
            return result & 0xFFFFFFFFFFFFFFFF, offset
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def encode_var_i64(v: int) -> bytes:
    # zigzag
    zz = ((v << 1) ^ (v >> 63)) & 0xFFFFFFFFFFFFFFFF
    return encode_var_u64(zz)


def decode_var_i64(b: bytes, offset: int = 0) -> tuple[int, int]:
    zz, offset = decode_var_u64(b, offset)
    v = (zz >> 1) ^ -(zz & 1)
    return v, offset


def encode_compact_bytes(data: bytes) -> bytes:
    return encode_var_i64(len(data)) + data


def decode_compact_bytes(b: bytes, offset: int = 0) -> tuple[bytes, int]:
    n, offset = decode_var_i64(b, offset)
    if n < 0 or offset + n > len(b):
        raise ValueError("compact bytes truncated")
    return b[offset : offset + n], offset + n


# ---------------------------------------------------------------------------
# numpy batch codecs — the coprocessor's row→column fast path
# ---------------------------------------------------------------------------

def encode_u64_batch(vals: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (n, 8) uint8 big-endian."""
    return vals.astype(">u8").view(np.uint8).reshape(-1, 8)


def decode_u64_batch(rows: np.ndarray) -> np.ndarray:
    """(n, 8) uint8 big-endian → (n,) uint64."""
    return np.ascontiguousarray(rows, dtype=np.uint8).view(">u8").reshape(-1).astype(np.uint64)


def encode_i64_batch(vals: np.ndarray) -> np.ndarray:
    u = vals.astype(np.int64).view(np.uint64) ^ np.uint64(SIGN_MASK)
    return encode_u64_batch(u)


def decode_i64_batch(rows: np.ndarray) -> np.ndarray:
    u = decode_u64_batch(rows) ^ np.uint64(SIGN_MASK)
    return u.view(np.int64)


def decode_f64_batch(rows: np.ndarray) -> np.ndarray:
    u = decode_u64_batch(rows)
    # encoded sign bit set ⇔ original value was non-negative
    was_nonneg = (u & np.uint64(SIGN_MASK)) != 0
    u = np.where(was_nonneg, u ^ np.uint64(SIGN_MASK), u ^ np.uint64(0xFFFFFFFFFFFFFFFF))
    return u.view(np.float64)


def encode_f64_batch(vals: np.ndarray) -> np.ndarray:
    """(n,) float64 → (n, 8) uint8 memcomparable encoding (encode_f64)."""
    u = np.ascontiguousarray(vals, dtype=np.float64).view(np.uint64)
    neg = (u & np.uint64(SIGN_MASK)) != 0
    u = np.where(neg, u ^ np.uint64(0xFFFFFFFFFFFFFFFF), u ^ np.uint64(SIGN_MASK))
    return encode_u64_batch(u)


def encode_var_u64_batch(vals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Batch LEB128: (n,) uint64 → (concatenated varint bytes, per-value
    byte lengths).  Byte-identical to ``encode_var_u64`` per element — the
    row-codec fast path uses it to emit whole columns without a Python loop
    per value."""
    v = np.ascontiguousarray(vals, dtype=np.uint64)
    n = len(v)
    if n == 0:
        return np.empty(0, np.uint8), np.empty(0, np.int64)
    lens = np.ones(n, np.int64)
    for k in range(1, 10):
        lens += (v >> np.uint64(7 * k)) != 0
    total = int(lens.sum())
    starts = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    row = np.repeat(np.arange(n), lens)
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, lens)
    groups = (v[row] >> (np.uint64(7) * within.astype(np.uint64))).astype(np.uint64)
    out = (groups & np.uint64(0x7F)).astype(np.uint8)
    cont = within < (lens[row] - 1)
    out[cont] |= np.uint8(0x80)
    return out, lens


def encode_var_i64_batch(vals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Batch zigzag varint (``encode_var_i64`` per element)."""
    u = np.ascontiguousarray(vals, dtype=np.int64).view(np.uint64)
    zz = (u << np.uint64(1)) ^ (np.uint64(0xFFFFFFFFFFFFFFFF) * (u >> np.uint64(63)))
    return encode_var_u64_batch(zz)
