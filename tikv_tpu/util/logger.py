"""Structured logging with user-data redaction.

Re-expression of ``log_wrappers/src/lib.rs`` + the TiKV log format RFC
(``components/tikv_util/src/logger``): log lines are
``[time] [LEVEL] [module] [event] [k=v] ...`` and **user keys/values never
reach the log verbatim unless the operator opts in**:

* redaction ON  → every key/value logged through ``key()``/``value()``
  prints as ``?``
* redaction "marker" → wrapped as ``‹hex›`` so support bundles can strip
  them later (lib.rs ``REDACT_INFO_LOG`` tri-state)
* redaction OFF → hex of the raw bytes (still never raw control bytes)

Use ``get_logger(module)`` and pass pre-wrapped values; plain fields are the
caller's responsibility to keep free of user data.
"""

from __future__ import annotations

import logging
import threading
import time

_redact = "off"  # "on" | "off" | "marker"
_mu = threading.Lock()


def set_redact_info_log(mode) -> None:
    """True/'on', False/'off', or 'marker'."""
    global _redact
    if mode is True:
        mode = "on"
    elif mode is False:
        mode = "off"
    if mode not in ("on", "off", "marker"):
        raise ValueError(f"bad redact mode {mode!r}")
    with _mu:
        _redact = mode


def redact_mode() -> str:
    return _redact


def key(k: bytes) -> str:
    """Render a user key for logging, honoring the redaction mode."""
    if _redact == "on":
        return "?"
    h = bytes(k).hex().upper()
    if _redact == "marker":
        return f"‹{h}›"  # ‹…›
    return h


value = key  # user values redact identically


class _Formatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        t = time.strftime("%Y/%m/%d %H:%M:%S", time.localtime(record.created))
        ms = int(record.msecs)
        fields = getattr(record, "kv", None) or {}
        tail = "".join(f" [{k}={v}]" for k, v in fields.items())
        return (
            f"[{t}.{ms:03d}] [{record.levelname}] [{record.name}] "
            f"[{record.getMessage()}]{tail}"
        )


_configured = False


def _ensure_configured() -> None:
    """Install the handler lazily on first *emit*, never at import time —
    get_logger at module scope must stay side-effect free for embedders."""
    global _configured
    if _configured:
        return
    with _mu:
        if not _configured:
            root = logging.getLogger("tikv_tpu")
            handler = logging.StreamHandler()
            handler.setFormatter(_Formatter())
            root.addHandler(handler)
            root.setLevel(logging.INFO)
            root.propagate = False
            _configured = True


def get_logger(module: str) -> "StructuredLogger":
    return StructuredLogger(logging.getLogger(f"tikv_tpu.{module}"))


class StructuredLogger:
    """``log.info("applied snapshot", region=2, key=key(k))`` →
    ``[...] [INFO] [tikv_tpu.raftstore] [applied snapshot] [region=2] [key=?]``"""

    __slots__ = ("_log",)

    def __init__(self, log: logging.Logger):
        self._log = log

    def _emit(self, level: int, event: str, kv: dict) -> None:
        _ensure_configured()
        if self._log.isEnabledFor(level):
            # log↔trace correlation (docs/tracing.md): lines emitted under
            # an active span carry its trace_id, so diagnostics.search_log
            # pivots from a trace straight to its log lines (and back, via
            # the slow log's trace ids).  One thread-local read when no
            # trace is active.
            from . import trace

            tid = trace.current_trace_id()
            if tid is not None and "trace_id" not in kv:
                kv = {**kv, "trace_id": tid}
            self._log.log(level, event, extra={"kv": kv})

    def debug(self, event: str, **kv) -> None:
        self._emit(logging.DEBUG, event, kv)

    def info(self, event: str, **kv) -> None:
        self._emit(logging.INFO, event, kv)

    def warn(self, event: str, **kv) -> None:
        self._emit(logging.WARNING, event, kv)

    def error(self, event: str, **kv) -> None:
        self._emit(logging.ERROR, event, kv)
