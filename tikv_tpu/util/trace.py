"""End-to-end distributed tracing plane (docs/tracing.md).

Re-expression of the reference's minitrace integration (TiKV v5.1 threads
trace spans through the kvproto request Context and surfaces them in the
slow log): causally-linked spans from the client wire frame through the
read-plane ladder, the coprocessor scheduler's queue lanes, device dispatch,
and the txn scheduler's raft propose→apply — ONE trace per request no matter
how many stores, threads, or micro-batches it crosses.

Model
-----
* A **trace** is a tree of spans sharing one ``trace_id``.  A **span** has a
  ``span_id``, a ``parent_id``, a monotonic start/duration, a wall-clock
  anchor (cross-store ordering), and typed tags.
* The **current span** is thread-local; ``span(name)`` nests under it.
  Thread/pool boundaries hand off EXPLICITLY: capture ``current_context()``
  on the submitting thread, ``attach(ctx)`` (or ``remote_span``) on the
  worker — implicit inheritance across pools would misattribute every
  borrowed thread.
* **Wire propagation**: ``inject(ctx)`` stamps ``trace_id``/``span_id``/
  ``sampled`` into a request context dict; the serving store's RPC layer
  joins the same trace via ``start_trace(..., ctx=ctx)``.  Read-plane
  forwards, device-owner hops and client retries therefore produce one
  trace spanning stores.
* **Fan-in** (shared-slot batch serving): a coalesced device dispatch is its
  own one-span trace (``fanin_span``) recording the participating parent
  trace ids; each rider gets a ``batched_into`` link pointing at it
  (``remote_span``).  That is the only honest shape — one dispatch span
  cannot be a child of N different parents.

Sampling
--------
Head-based: a fresh trace is recorded iff ``random() < sample_rate`` marks
it ``sampled`` — but when the rate is in (0, 1) EVERY request still records
spans into a bounded live table, because tail-based **promotion** keeps any
trace whose root crosses ``slow_threshold_s`` even when the head decision
said drop ("the slow request you could not predict").  ``sample_rate == 0``
turns the plane off: every entry point is ONE branch returning the no-op
span, no allocation beyond the call itself.

The tracer's lock is a LEAF by construction — span operations touch only
tracer state, never another subsystem's lock — so spans are safe to open or
finish while holding scheduler/cache/raft locks (the sanitizer's order graph
can never find a cycle through it).
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque

from ..analysis.sanitizer import make_lock

__all__ = [
    "Span", "attach", "begin", "current", "current_context",
    "current_trace_id", "enabled", "fanin_span", "inject", "record",
    "remote_span", "sample_rate", "set_sample_rate", "set_slow_threshold",
    "slow_threshold", "snapshot", "span", "start_trace", "timeline", "TRACER",
]

#: per-trace span cap: one runaway loop must not balloon the live table
MAX_SPANS = 128
#: live (unfinished) trace cap: beyond it, new traces are dropped+counted
MAX_LIVE = 2048
#: finished-trace rings (recent = every kept trace, slow = promoted/slow)
RING = 64

_CTX_KEYS = ("trace_id", "span_id", "sampled")


def _count(outcome: str) -> None:
    from .metrics import REGISTRY

    REGISTRY.counter(
        "tikv_trace_total",
        "Trace head/tail sampling decisions at trace completion, by outcome",
    ).inc(outcome=outcome)


class _Noop:
    """The disabled-path span: one shared instance, every operation a no-op.
    Falsy so hot call sites can skip tag computation with ``if sp:``."""

    __slots__ = ()

    def __bool__(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **kv):
        return self

    def link(self, kind, ref):
        return self

    def finish(self, end=None):
        return None

    def child(self, name, start=None, **tags):
        return self

    def record(self, name, start, end, **tags):
        return self

    def active(self):
        return self

    context = None


NOOP = _Noop()


class _Active:
    """Span.active(): current-span push/pop without finishing."""

    __slots__ = ("_sp", "_prev")

    def __init__(self, sp: "Span"):
        self._sp = sp
        self._prev = None

    def __enter__(self):
        st = self._sp._tracer._state
        self._prev = getattr(st, "cur", None)
        st.cur = self._sp
        return self._sp

    def __exit__(self, *exc):
        st = self._sp._tracer._state
        if getattr(st, "cur", None) is self._sp:
            st.cur = self._prev
        return False


class _Rec:
    """One live trace: its spans plus the open-span refcount that decides
    when the trace is complete and the sampling verdict applies."""

    __slots__ = ("trace_id", "sampled", "spans", "open", "had_root",
                 "root_dur", "truncated", "t0")

    def __init__(self, trace_id: str, sampled: bool):
        self.trace_id = trace_id
        self.sampled = sampled
        self.spans: list[Span] = []
        self.open = 0
        self.had_root = False
        self.root_dur: float | None = None
        self.truncated = 0
        self.t0 = time.time()


class Span:
    __slots__ = ("rec", "name", "span_id", "parent_id", "wall", "t0",
                 "dur", "tags", "root", "_tracer", "_prev", "_pushed")

    def __init__(self, tracer: "Tracer", rec: _Rec, name: str,
                 parent_id: str | None, root: bool,
                 start: float | None = None, tags: dict | None = None):
        self.rec = rec
        self.name = name
        self.span_id = tracer._new_id()
        self.parent_id = parent_id
        self.t0 = time.perf_counter() if start is None else start
        self.wall = time.time() - (time.perf_counter() - self.t0)
        self.dur: float | None = None
        self.tags = dict(tags) if tags else {}
        self.root = root
        self._tracer = tracer
        self._prev = None
        self._pushed = False

    def __bool__(self):
        return True

    @property
    def context(self) -> dict:
        return {"trace_id": self.rec.trace_id, "span_id": self.span_id,
                "sampled": self.rec.sampled}

    def tag(self, **kv) -> "Span":
        self.tags.update(kv)
        return self

    def link(self, kind: str, ref: str) -> "Span":
        self.tags[kind] = ref
        return self

    def child(self, name: str, start: float | None = None, **tags) -> "Span":
        """A child of THIS span regardless of the thread-local current —
        the explicit form the RPC layer uses for its stage spans."""
        return self._tracer._child(self.rec, self.span_id, name, tags,
                                   start=start)

    def record(self, name: str, start: float, end: float, **tags) -> "Span":
        """A finished child with explicit perf_counter bounds (stages
        measured before/after the span tree could be current)."""
        sp = self.child(name, start=start, **tags)
        sp.finish(end=end)
        return sp

    def active(self) -> "_Active":
        """Push this span as the thread-local current for a block WITHOUT
        finishing it on exit — the cross-thread activation used when a pool
        worker executes under a span its submitter owns."""
        return _Active(self)

    # -- context-manager use (same-thread nesting) --------------------------

    def __enter__(self) -> "Span":
        st = self._tracer._state
        self._prev = getattr(st, "cur", None)
        st.cur = self
        self._pushed = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None and "error" not in self.tags:
            self.tags["error"] = repr(exc)
        st = self._tracer._state
        if getattr(st, "cur", None) is self:
            st.cur = self._prev
        self._pushed = False
        self.finish()
        return False

    # -- explicit finish (cross-thread handles: raft apply callbacks) -------

    def finish(self, end: float | None = None) -> None:
        if self.dur is not None:
            return  # fast path; the real exactly-once gate is in _span_done
        self._tracer._span_done(
            self, time.perf_counter() if end is None else end)

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": round(self.wall, 6),
            "duration_ms": round((self.dur or 0.0) * 1000, 3),
            "tags": {k: _plain(v) for k, v in self.tags.items()},
        }


def _plain(v):
    """Wire/JSON-codable tag value (the debug_traces RPC re-frames these)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).hex()
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    return repr(v)


class Tracer:
    """Process-global trace store: live table + finished rings."""

    def __init__(self, sample_rate: float | None = None,
                 slow_threshold_s: float | None = None):
        if sample_rate is None:
            sample_rate = float(os.environ.get("TIKV_TPU_TRACE_SAMPLE", "0.01"))
        if slow_threshold_s is None:
            slow_threshold_s = float(
                os.environ.get("TIKV_TPU_TRACE_SLOW_S", "0.3"))
        self._rate = max(0.0, min(1.0, sample_rate))
        self._slow_s = slow_threshold_s
        self._mu = make_lock("util.trace")
        self._state = threading.local()
        self._live: dict[str, _Rec] = {}
        self._recent: deque[dict] = deque(maxlen=RING)
        self._slow: deque[dict] = deque(maxlen=RING)
        self._rng = random.Random()
        self._idgen = random.Random()

    # -- knobs (online-config controller + ctl.py trace set-sample-rate) ----

    def set_sample_rate(self, rate: float) -> None:
        self._rate = max(0.0, min(1.0, float(rate)))

    def sample_rate(self) -> float:
        return self._rate

    def set_slow_threshold(self, seconds: float) -> None:
        self._slow_s = float(seconds)

    def slow_threshold(self) -> float:
        return self._slow_s

    def enabled(self) -> bool:
        return self._rate > 0.0

    # -- ids ----------------------------------------------------------------

    def _new_id(self) -> str:
        return f"{self._idgen.getrandbits(64):016x}"

    def _room_locked(self) -> bool:
        """Live-table admission (caller holds the lock): at the cap, evict
        ONE stale record (open > 60s — a span handle leaked by a crashed
        worker) rather than letting a slow leak starve all future traces."""
        if len(self._live) < MAX_LIVE:
            return True
        now = time.time()
        oldest = min(self._live.values(), key=lambda r: r.t0, default=None)
        if oldest is not None and now - oldest.t0 > 60.0:
            del self._live[oldest.trace_id]
            return True
        return False

    # -- trace/span creation ------------------------------------------------

    def start_trace(self, name: str, ctx: dict | None = None,
                    start: float | None = None, **tags):
        """Root (or wire-joined) span of a request on this store.

        ``ctx`` carrying ``trace_id`` + ``sampled`` JOINS the remote trace
        (the span parents onto the remote ``span_id``); otherwise a fresh
        trace starts iff sampling is on.  Joined spans are not roots — the
        originating store's root closes the trace."""
        # join whenever the context names a trace this process should record:
        # a head-SAMPLED trace always (keeps distributed traces whole even on
        # a rate-0 store), an unsampled one only while tail promotion is on
        # locally (rate > 0) — its spans matter exactly when the request
        # turns out slow
        joined = bool(ctx) and bool(ctx.get("trace_id")) and (
            bool(ctx.get("sampled")) or self._rate > 0.0)
        if not joined and self._rate <= 0.0:
            return NOOP
        with self._mu:
            if joined:
                rec = self._live.get(ctx["trace_id"])
                if rec is None and self._room_locked():
                    # cross-process join: this store records its fragment of
                    # the trace (committed rootless when its spans close)
                    rec = _Rec(ctx["trace_id"], bool(ctx.get("sampled")))
                    self._live[rec.trace_id] = rec
                parent = ctx.get("span_id")
                root = False
            else:
                rec = None
                if self._room_locked():
                    rec = _Rec(self._new_id(),
                               self._rng.random() < self._rate)
                    self._live[rec.trace_id] = rec
                parent = None
                root = True
            if rec is not None:
                rec.open += 1
                rec.had_root = rec.had_root or root
        if rec is None:
            _count("dropped")
            return NOOP
        sp = Span(self, rec, name, parent, root, start=start, tags=tags)
        self._gauge()
        return sp

    def span(self, name: str, **tags):
        """Child of the current span; NOOP when no trace is active here."""
        cur = getattr(self._state, "cur", None)
        if cur is None:
            return NOOP
        return self._child(cur.rec, cur.span_id, name, tags)

    def begin(self, name: str, **tags):
        """Like :meth:`span` but NOT pushed as current: a handle the caller
        finishes explicitly, possibly from another thread (the raft write
        callback).  The tracer lock is a leaf, so finishing from any thread
        is safe."""
        cur = getattr(self._state, "cur", None)
        if cur is None:
            return NOOP
        return self._child(cur.rec, cur.span_id, name, tags)

    def record(self, name: str, start: float, end: float, **tags):
        """A finished child span with explicit perf_counter bounds — the
        wire stages measured before a span could exist (frame decode)."""
        cur = getattr(self._state, "cur", None)
        if cur is None:
            return NOOP
        sp = self._child(cur.rec, cur.span_id, name, tags, start=start)
        sp.finish(end=end)
        return sp

    def _child(self, rec: _Rec, parent_id: str | None, name: str,
               tags: dict, start: float | None = None) -> Span:
        with self._mu:
            rec.open += 1
        return Span(self, rec, name, parent_id, False, start=start, tags=tags)

    # -- explicit handoff ----------------------------------------------------

    def current(self):
        return getattr(self._state, "cur", None)

    def current_context(self) -> dict | None:
        cur = getattr(self._state, "cur", None)
        return cur.context if cur is not None else None

    def current_trace_id(self) -> str | None:
        cur = getattr(self._state, "cur", None)
        return cur.rec.trace_id if cur is not None else None

    def inject(self, ctx: dict) -> dict:
        """Stamp the current span's identity into a request context dict
        (mutates and returns it).  No-op without an active span."""
        cur = getattr(self._state, "cur", None)
        if cur is not None:
            ctx["trace_id"] = cur.rec.trace_id
            ctx["span_id"] = cur.span_id
            ctx["sampled"] = cur.rec.sampled
        return ctx

    def attach(self, ctx: dict | None) -> "_Attach":
        """Make a captured context current for a block on THIS thread (the
        pool-boundary handoff): spans opened inside nest under the remote
        parent.  ``attach(None)`` is a no-op block."""
        return _Attach(self, ctx)

    def remote_span(self, ctx: dict | None, name: str,
                    start: float | None = None, end: float | None = None,
                    **tags):
        """Record a span directly into the trace named by ``ctx`` without
        touching this thread's current stack — how a dispatcher thread
        stamps per-rider spans for work it served on their behalf.  Applies
        to unsampled live records too: tail promotion exists to keep
        exactly these phases when the request turns out slow."""
        if not ctx or not ctx.get("trace_id"):
            return NOOP
        with self._mu:
            rec = self._live.get(ctx["trace_id"])
            if rec is None:
                return NOOP  # trace already finished (or cross-process)
            rec.open += 1
        sp = Span(self, rec, name, ctx.get("span_id"), False,
                  start=start, tags=tags)
        if end is not None or start is not None:
            sp.finish(end=end)
        return sp

    def fanin_span(self, name: str, parents: list[dict | None], **tags):
        """The shared device-dispatch span: a one-span trace of its own,
        tagged with every participating parent trace id.  Sampled iff any
        participant is (a batch serving one kept trace must be kept)."""
        live = [p for p in parents if p and p.get("trace_id")]
        if not live:
            return NOOP
        sampled = any(p.get("sampled") for p in live)
        if not sampled and self._rate <= 0.0:
            return NOOP
        with self._mu:
            rec = None
            if self._room_locked():
                rec = _Rec(self._new_id(), sampled)
                rec.had_root = True
                rec.open = 1
                self._live[rec.trace_id] = rec
        if rec is None:
            _count("dropped")
            return NOOP
        tags = dict(tags)
        tags["participants"] = sorted({p["trace_id"] for p in live})
        return Span(self, rec, name, None, True, tags=tags)

    # -- completion ----------------------------------------------------------

    def _span_done(self, sp: Span, t_end: float) -> None:
        rec = sp.rec
        finished = None
        with self._mu:
            if sp.dur is not None:
                return  # exactly-once under the lock: a racing double
                # finish (apply callback vs. propose-timeout cleanup) must
                # not double-decrement the record's open count
            sp.dur = t_end - sp.t0
            if len(rec.spans) < MAX_SPANS:
                rec.spans.append(sp)
            else:
                rec.truncated += 1
            rec.open -= 1
            if sp.root:
                rec.root_dur = sp.dur
            if rec.open <= 0 and self._live.get(rec.trace_id) is rec:
                del self._live[rec.trace_id]
                finished = rec
        if finished is not None:
            self._commit(finished)

    def _commit(self, rec: _Rec) -> None:
        dur = rec.root_dur
        if dur is None and rec.spans:
            # rootless (joined-only, cross-process): the local fragment's
            # wall extent stands in for the root
            dur = max((s.dur or 0.0) for s in rec.spans)
        slow = dur is not None and dur >= self._slow_s
        if not rec.sampled and not slow:
            _count("dropped")
            self._gauge()
            return
        d = self._trace_dict(rec, dur, slow)
        with self._mu:
            if rec.sampled:
                self._recent.append(d)
            if slow:
                self._slow.append(d)
        _count("sampled" if rec.sampled else "promoted")
        self._gauge()

    def _trace_dict(self, rec: _Rec, dur, slow: bool) -> dict:
        return {
            "trace_id": rec.trace_id,
            "sampled": rec.sampled,
            "promoted": slow and not rec.sampled,
            "slow": slow,
            "start": round(rec.t0, 6),
            "duration_ms": round((dur or 0.0) * 1000, 3),
            "truncated": rec.truncated,
            "spans": [s.to_dict() for s in
                      sorted(rec.spans, key=lambda s: s.wall)],
        }

    def _gauge(self) -> None:
        from .metrics import REGISTRY

        g = REGISTRY.gauge(
            "tikv_trace_ring_traces",
            "Traces held per tracer ring (live = still open)",
        )
        g.set(len(self._live), ring="live")
        g.set(len(self._recent), ring="recent")
        g.set(len(self._slow), ring="slow")

    # -- export (debug_traces RPC, /debug/traces, ctl.py trace) --------------

    def snapshot(self, limit: int = 20) -> dict:
        with self._mu:
            # limit<=0 means none: [-0:] would slice the WHOLE ring
            recent = list(self._recent)[-limit:] if limit > 0 else []
            slow = list(self._slow)[-limit:] if limit > 0 else []
            live = len(self._live)
        return {
            "sample_rate": self._rate,
            "slow_threshold_s": self._slow_s,
            "live": live,
            "recent": recent,
            "slow": slow,
        }

    def get(self, trace_id: str) -> dict | None:
        with self._mu:
            for ring in (self._slow, self._recent):
                for d in reversed(ring):
                    if d["trace_id"] == trace_id:
                        return d
        return None

    def reset(self) -> None:
        """Test isolation: drop every live record and both rings."""
        with self._mu:
            self._live.clear()
            self._recent.clear()
            self._slow.clear()
        self._state = threading.local()


def timeline(trace: dict) -> str:
    """Indented text rendering of one trace dict: children nested under
    parents, ordered by wall-clock start, durations in ms."""
    spans = trace.get("spans", [])
    by_parent: dict = {}
    ids = {s["span_id"] for s in spans}
    for s in spans:
        parent = s["parent_id"] if s["parent_id"] in ids else None
        by_parent.setdefault(parent, []).append(s)
    t0 = min((s["start"] for s in spans), default=trace.get("start", 0.0))
    out = [f"trace {trace['trace_id']} "
           f"({trace.get('duration_ms', 0)}ms"
           f"{', slow' if trace.get('slow') else ''}"
           f"{', promoted' if trace.get('promoted') else ''})"]

    def walk(parent, depth):
        for s in sorted(by_parent.get(parent, ()), key=lambda s: s["start"]):
            off = (s["start"] - t0) * 1000
            tags = " ".join(f"{k}={v}" for k, v in sorted(s["tags"].items()))
            out.append(f"{'  ' * depth}+{off:9.3f}ms {s['name']} "
                       f"[{s['duration_ms']}ms]{' ' + tags if tags else ''}")
            walk(s["span_id"], depth + 1)

    walk(None, 1)
    return "\n".join(out)


class _Attach:
    __slots__ = ("_tracer", "_sp", "_ctx")

    def __init__(self, tracer: Tracer, ctx: dict | None):
        self._tracer = tracer
        self._ctx = ctx
        self._sp = None

    def __enter__(self):
        ctx = self._ctx
        # unsampled live records attach too — their worker-side spans are
        # what tail promotion retroactively keeps on a slow request
        if not ctx or not ctx.get("trace_id"):
            return NOOP
        with self._tracer._mu:
            rec = self._tracer._live.get(ctx["trace_id"])
            if rec is None:
                return NOOP
        # a zero-cost anchor span is NOT created: attaching just points the
        # thread-local current at the remote parent so children nest there
        sp = Span.__new__(Span)
        sp.rec = rec
        sp.name = "<attached>"
        sp.span_id = ctx.get("span_id")
        sp.parent_id = None
        sp.t0 = time.perf_counter()
        sp.wall = time.time()
        sp.dur = 0.0  # never finished/recorded: a handle, not a span
        sp.tags = {}
        sp.root = False
        sp._tracer = self._tracer
        sp._prev = getattr(self._tracer._state, "cur", None)
        sp._pushed = True
        self._tracer._state.cur = sp
        self._sp = sp
        return sp

    def __exit__(self, *exc):
        if self._sp is not None:
            st = self._tracer._state
            if getattr(st, "cur", None) is self._sp:
                st.cur = self._sp._prev
            self._sp = None
        return False


TRACER = Tracer()

# -- module-level facade (the call-site API) --------------------------------


def enabled() -> bool:
    return TRACER.enabled()


def sample_rate() -> float:
    return TRACER.sample_rate()


def set_sample_rate(rate: float) -> None:
    TRACER.set_sample_rate(rate)


def slow_threshold() -> float:
    return TRACER.slow_threshold()


def set_slow_threshold(seconds: float) -> None:
    TRACER.set_slow_threshold(seconds)


def start_trace(name: str, ctx: dict | None = None,
                start: float | None = None, **tags):
    return TRACER.start_trace(name, ctx=ctx, start=start, **tags)


def span(name: str, **tags):
    return TRACER.span(name, **tags)


def begin(name: str, **tags):
    return TRACER.begin(name, **tags)


def record(name: str, start: float, end: float, **tags):
    return TRACER.record(name, start, end, **tags)


def current():
    return TRACER.current()


def current_context():
    return TRACER.current_context()


def current_trace_id():
    return TRACER.current_trace_id()


def inject(ctx: dict) -> dict:
    return TRACER.inject(ctx)


def attach(ctx: dict | None):
    return TRACER.attach(ctx)


def remote_span(ctx: dict | None, name: str, start: float | None = None,
                end: float | None = None, **tags):
    return TRACER.remote_span(ctx, name, start=start, end=end, **tags)


def fanin_span(name: str, parents: list, **tags):
    return TRACER.fanin_span(name, parents, **tags)


def snapshot(limit: int = 20) -> dict:
    return TRACER.snapshot(limit)
