"""Configuration system with validation and online reconfiguration.

Re-expression of ``src/config.rs`` (TiKvConfig :2297, ConfigController :3115)
+ ``components/online_config``: a nested dataclass tree loaded from TOML,
``validate()`` checks, and a ``ConfigController`` that diffs updates and
dispatches changed sections to registered per-module ConfigManagers — the
mechanism behind POST /config online reconfig.
"""

from __future__ import annotations

import threading
from dataclasses import asdict, dataclass, field, fields, is_dataclass

try:  # tomllib is 3.11+; this image runs 3.10 and bakes no tomli
    import tomllib
except ImportError:  # pragma: no cover - depends on interpreter version
    tomllib = None


def _toml_loads_minimal(text: str) -> dict:
    """Subset TOML parser used only when ``tomllib`` is unavailable: flat
    ``[section]`` tables and ``key = value`` scalars (strings, booleans,
    ints, floats) — exactly the shape of this project's config files."""
    def strip_comment(line: str) -> str:
        # only strip a '#' that sits outside quoted strings
        quote = None
        for i, ch in enumerate(line):
            if quote is None:
                if ch in "\"'":
                    quote = ch
                elif ch == "#":
                    return line[:i]
            elif ch == quote:
                quote = None
        return line

    out: dict = {}
    table = out
    for raw in text.splitlines():
        line = strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            table = out
            for part in line[1:-1].strip().split("."):
                table = table.setdefault(part.strip(), {})
            continue
        if "=" not in line:
            raise ValueError(f"unsupported TOML line: {raw!r}")
        key, val = (s.strip() for s in line.split("=", 1))
        key = key.strip('"')
        if val.startswith(('"', "'")) and val.endswith(val[0]) and len(val) >= 2:
            table[key] = val[1:-1]
        elif val in ("true", "false"):
            table[key] = val == "true"
        else:
            try:
                table[key] = int(val, 0)
            except ValueError:
                table[key] = float(val)
    return out


@dataclass
class ReadPoolConfig:
    unified_max_threads: int = 8
    batch_max_size: int = 1024


@dataclass
class CoprocessorConfig:
    enable_device: bool = True
    block_rows: int = 1 << 16
    region_split_keys: int = 960000
    region_max_keys: int = 1440000
    cache_entries: int = 64
    # scheduler per-lane linger windows (docs/copr_scheduler.md) — online
    # through POST /config, and the geometry auto-tuner's hill-climb knobs
    # (docs/cost_router.md)
    max_wait_s: float = 0.004
    high_max_wait_s: float = 0.001
    low_max_wait_s: float = 0.02


@dataclass
class RaftstoreConfig:
    election_tick: int = 10
    heartbeat_tick: int = 2
    tick_interval_ms: int = 50
    region_split_check_diff: int = 8


@dataclass
class StorageConfig:
    scheduler_concurrency: int = 256
    scheduler_worker_pool_size: int = 4
    ttl_check_interval_s: int = 60


@dataclass
class GcConfig:
    batch_keys: int = 512
    auto_gc_interval_s: float = 1.0


@dataclass
class ServerConfig:
    addr: str = "127.0.0.1:20160"
    grpc_concurrency: int = 8
    status_addr: str = "127.0.0.1:20180"


@dataclass
class TraceConfig:
    """trace.* — the distributed tracing plane (docs/tracing.md).  Both
    knobs reconfigure online through the ConfigController (``ctl.py trace
    set-sample-rate`` POSTs here)."""

    sample_rate: float = 0.01
    slow_threshold_s: float = 0.3


@dataclass
class OverloadSection:
    """overload.* — the overload control plane (docs/robustness.md
    "Overload").  Every scalar reconfigures online through the
    ConfigController; rates are the DEFAULT tenant quota (0 = unlimited),
    per-tenant overrides go through ``OverloadControl.set_quota``."""

    enabled: bool = False
    requests_per_s: float = 0.0
    read_bytes_per_s: float = 0.0
    burst_s: float = 1.0
    max_wait_s: float = 0.02
    max_priority: str = "high"
    adaptive: bool = True
    min_scale: float = 0.1
    window_s: float = 1.0


@dataclass
class SecuritySection:
    """security.* (components/security/src/lib.rs SecurityConfig)."""

    ca_path: str = ""
    cert_path: str = ""
    key_path: str = ""
    cert_allowed_cn: list = field(default_factory=list)
    redact_info_log: str = "off"  # off | on | marker


@dataclass
class TikvConfig:
    server: ServerConfig = field(default_factory=ServerConfig)
    raftstore: RaftstoreConfig = field(default_factory=RaftstoreConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    coprocessor: CoprocessorConfig = field(default_factory=CoprocessorConfig)
    readpool: ReadPoolConfig = field(default_factory=ReadPoolConfig)
    gc: GcConfig = field(default_factory=GcConfig)
    security: SecuritySection = field(default_factory=SecuritySection)
    trace: TraceConfig = field(default_factory=TraceConfig)
    overload: OverloadSection = field(default_factory=OverloadSection)

    def apply_security(self):
        """Make the [security] section take effect process-wide: returns the
        SecurityConfig (or None for plaintext) and applies redact_info_log."""
        from . import logger as slog

        slog.set_redact_info_log(self.security.redact_info_log)
        sc = self.security_config()
        return sc if sc.enabled else None

    def security_config(self):
        from ..server.security import SecurityConfig

        sc = SecurityConfig(
            ca_path=self.security.ca_path,
            cert_path=self.security.cert_path,
            key_path=self.security.key_path,
            cert_allowed_cn=set(self.security.cert_allowed_cn),
        )
        sc.validate()
        return sc

    def validate(self) -> None:
        if self.raftstore.heartbeat_tick >= self.raftstore.election_tick:
            raise ValueError("heartbeat_tick must be < election_tick")
        self.security_config()
        if self.security.redact_info_log not in ("off", "on", "marker"):
            raise ValueError("security.redact_info_log must be off|on|marker")
        if self.coprocessor.block_rows <= 0 or self.coprocessor.block_rows & (self.coprocessor.block_rows - 1):
            raise ValueError("coprocessor.block_rows must be a power of two")
        if not (1 << 8) <= self.coprocessor.block_rows <= (1 << 20):
            # the auto-tuner's hill-climb bounds double as operator sanity
            raise ValueError(
                "coprocessor.block_rows must be in [2^8, 2^20]")
        for name in ("max_wait_s", "high_max_wait_s", "low_max_wait_s"):
            v = getattr(self.coprocessor, name)
            if not 0.0 < v <= 1.0:
                raise ValueError(f"coprocessor.{name} must be in (0, 1.0]")
        if self.storage.scheduler_concurrency <= 0:
            raise ValueError("storage.scheduler_concurrency must be positive")
        if self.coprocessor.region_split_keys > self.coprocessor.region_max_keys:
            raise ValueError("region_split_keys must be <= region_max_keys")
        if not 0.0 <= self.trace.sample_rate <= 1.0:
            raise ValueError("trace.sample_rate must be in [0, 1]")
        if self.trace.slow_threshold_s < 0:
            raise ValueError("trace.slow_threshold_s must be >= 0")
        ov = self.overload
        if ov.max_priority not in ("high", "normal", "low"):
            raise ValueError("overload.max_priority must be high|normal|low")
        if ov.requests_per_s < 0 or ov.read_bytes_per_s < 0:
            raise ValueError("overload rates must be >= 0 (0 = unlimited)")
        if not 0.0 < ov.min_scale <= 1.0:
            raise ValueError("overload.min_scale must be in (0, 1]")
        if ov.burst_s <= 0 or ov.window_s <= 0 or ov.max_wait_s < 0:
            raise ValueError(
                "overload.burst_s/window_s must be > 0, max_wait_s >= 0")

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict, strict: bool = True) -> "TikvConfig":
        cfg = cls()
        unknown: list[str] = []
        _merge(cfg, d, unknown, "")
        if strict and unknown:
            raise ValueError(f"unknown config keys: {unknown}")
        return cfg

    @classmethod
    def from_toml(cls, text: str, strict: bool = True) -> "TikvConfig":
        loads = tomllib.loads if tomllib is not None else _toml_loads_minimal
        return cls.from_dict(loads(text), strict)


def _merge(obj, d: dict, unknown: list[str], prefix: str) -> None:
    names = {f.name: f for f in fields(obj)}
    for k, v in d.items():
        key = k.replace("-", "_")
        if key not in names:
            unknown.append(prefix + k)
            continue
        cur = getattr(obj, key)
        if is_dataclass(cur):
            if not isinstance(v, dict):
                unknown.append(prefix + k)
                continue
            _merge(cur, v, unknown, prefix + k + ".")
        else:
            setattr(obj, key, v)


class ConfigController:
    """Online reconfig dispatch (config.rs:3115): diff an update against the
    current config and notify each module whose section changed."""

    def __init__(self, config: TikvConfig):
        self._mu = threading.Lock()
        self.config = config
        self._managers: dict[str, callable] = {}

    def register(self, section: str, on_change) -> None:
        """on_change(changed: dict) is called with the section's changed keys."""
        self._managers[section] = on_change

    def update(self, changes: dict) -> dict:
        """changes: {"section.key": value} or nested dicts. Returns the diff
        applied.  Validation runs on a copy first — bad updates change nothing."""
        with self._mu:
            nested: dict = {}
            for k, v in changes.items():
                if isinstance(v, dict):
                    nested.setdefault(k, {}).update(v)
                else:
                    sect, _, key = k.partition(".")
                    if not key:
                        raise ValueError(f"not a section.key path: {k}")
                    nested.setdefault(sect, {})[key] = v
            candidate = TikvConfig.from_dict(self.config.to_dict(), strict=False)
            _merge_known(candidate, nested)
            candidate.validate()
            diff = _diff(self.config.to_dict(), candidate.to_dict())
            self.config = candidate
            for section, changed in diff.items():
                cb = self._managers.get(section)
                if cb is not None:
                    cb(changed)
            return diff


def _merge_known(cfg: TikvConfig, nested: dict) -> None:
    unknown: list[str] = []
    _merge(cfg, nested, unknown, "")
    if unknown:
        raise ValueError(f"unknown config keys: {unknown}")


def _diff(old: dict, new: dict) -> dict:
    out: dict = {}
    for sect, vals in new.items():
        if not isinstance(vals, dict):
            continue
        changed = {k: v for k, v in vals.items() if old.get(sect, {}).get(k) != v}
        if changed:
            out[sect] = changed
    return out
