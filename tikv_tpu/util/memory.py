"""Memory accounting: quotas and a hierarchical usage trace.

Re-expression of ``components/tikv_util/src/memory.rs`` (``MemoryQuota``,
``HeapSize``/``MemoryTrace``) and the server's memory-usage high-water check
(``components/server/src/server.rs:129-131``): subsystems attribute their
resident bytes to named nodes of a tree rooted at the store, quotas bound
individual consumers (CDC sinks, apply batches), and a high-water callback
fires when the tracked total crosses the configured mark so the store can
shed load (flush memtables, drop caches) instead of growing unboundedly.
"""

from __future__ import annotations

import threading
from typing import Callable


class MemoryQuotaExceeded(RuntimeError):
    pass


class MemoryQuota:
    """A byte budget shared by one consumer class (memory.rs MemoryQuota):
    ``alloc`` either reserves or reports failure — the caller decides whether
    to block, shed, or error.  ``free`` returns capacity and wakes blocked
    allocators."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._used = 0
        self._cv = threading.Condition()

    def in_use(self) -> int:
        with self._cv:
            return self._used

    def alloc(self, n: int) -> bool:
        with self._cv:
            if self._used + n > self.capacity:
                return False
            self._used += n
            return True

    def alloc_force(self, n: int) -> None:
        """Reserve even past capacity (the reference's force variant for
        records that must not be dropped, e.g. resolved-ts events)."""
        with self._cv:
            self._used += n

    def alloc_wait(self, n: int, timeout: float | None = None,
                   cancelled: Callable[[], bool] | None = None) -> bool:
        """Block until the reservation fits (producer pacing).  Returns False
        on timeout or when ``cancelled()`` turns true."""
        deadline = None if timeout is None else (threading.TIMEOUT_MAX
                                                 if timeout < 0 else timeout)
        with self._cv:
            waited = 0.0
            while self._used + n > self.capacity:
                if cancelled is not None and cancelled():
                    return False
                step = 0.05
                if deadline is not None and waited + step > deadline:
                    return False
                self._cv.wait(step)
                waited += step
            self._used += n
            return True

    def free(self, n: int) -> None:
        with self._cv:
            self._used = max(0, self._used - n)
            self._cv.notify_all()


class MemoryTrace:
    """A named node in the store's memory-attribution tree (memory.rs
    MemoryTrace): leaves accumulate bytes via add/sub or a ``provider``
    callable (for subsystems that already track their own residency, e.g.
    the native engine's mem_bytes); ``sum`` aggregates the subtree."""

    def __init__(self, name: str, provider: Callable[[], int] | None = None):
        self.name = name
        self._provider = provider
        self._bytes = 0
        self._mu = threading.Lock()
        self.children: dict[str, MemoryTrace] = {}
        self._root: StoreMemoryTrace | None = None

    def child(self, name: str, provider: Callable[[], int] | None = None) -> "MemoryTrace":
        with self._mu:
            c = self.children.get(name)
            if c is None:
                c = MemoryTrace(name, provider)
                c._root = self._root
                self.children[name] = c
            return c

    def add(self, n: int) -> None:
        with self._mu:
            self._bytes += n
        root = self._root
        if root is not None and n > 0:
            root._maybe_high_water()

    def sub(self, n: int) -> None:
        with self._mu:
            self._bytes = max(0, self._bytes - n)

    def local(self) -> int:
        with self._mu:
            own = self._bytes
        if self._provider is not None:
            try:
                own += int(self._provider())
            except Exception:  # noqa: BLE001 — a dead provider reports 0
                pass
        return own

    def sum(self) -> int:
        total = self.local()
        with self._mu:
            kids = list(self.children.values())
        return total + sum(c.sum() for c in kids)

    def snapshot(self) -> dict:
        with self._mu:
            kids = list(self.children.values())
        out = {"name": self.name, "bytes": self.local(), "total": self.sum()}
        if kids:
            out["children"] = [c.snapshot() for c in kids]
        return out


class StoreMemoryTrace(MemoryTrace):
    """The tree root, owning the high-water trigger: when the aggregated
    total first crosses ``high_water_bytes`` the callback fires (once per
    excursion — re-arms after usage falls below the mark), mirroring the
    reference's memory-usage-limit check at server assembly."""

    def __init__(self, name: str = "store"):
        super().__init__(name)
        self._root = self
        self.high_water_bytes: int | None = None
        self._on_high_water: Callable[[int], None] | None = None
        self._armed = True
        self._hw_mu = threading.Lock()

    def set_high_water(self, bytes_: int, callback: Callable[[int], None]) -> None:
        self.high_water_bytes = int(bytes_)
        self._on_high_water = callback
        self._armed = True

    def _maybe_high_water(self) -> None:
        hw = self.high_water_bytes
        cb = self._on_high_water
        if hw is None or cb is None:
            return
        with self._hw_mu:
            total = self.sum()
            if total >= hw and self._armed:
                self._armed = False
            elif total < hw:
                self._armed = True
                return
            else:
                return
        try:
            cb(total)
        except Exception:  # noqa: BLE001 — shedding must not break the adder
            pass

    def poll(self) -> None:
        """Re-evaluate the high-water condition for provider-driven growth
        (providers change without add() calls); call from a heartbeat."""
        self._maybe_high_water()
