"""Physical key-space layout.

Mirrors the reference's ``components/keys/src/lib.rs:22-39``: user data lives
under a ``z`` prefix so that store-local metadata (``0x01`` prefix) sorts before
all data and never collides with it.  Raft metadata per region lives under
``0x01 0x02`` / ``0x01 0x03`` prefixes keyed by the region id.
"""

from __future__ import annotations

from .codec import decode_u64, encode_u64

# store-local keys
LOCAL_PREFIX = b"\x01"
LOCAL_MIN_KEY = LOCAL_PREFIX
LOCAL_MAX_KEY = b"\x02"

DATA_PREFIX = b"z"
DATA_PREFIX_KEY = DATA_PREFIX
DATA_MIN_KEY = DATA_PREFIX
DATA_MAX_KEY = b"{"  # DATA_PREFIX + 1

MIN_KEY = b""
MAX_KEY = b"\xff" * 9

# local sub-prefixes (under LOCAL_PREFIX)
STORE_IDENT_KEY = LOCAL_PREFIX + b"\x01"
PREPARE_BOOTSTRAP_KEY = LOCAL_PREFIX + b"\x02"
REGION_RAFT_PREFIX = b"\x02"  # 0x01 0x02 region_id suffix
REGION_META_PREFIX = b"\x03"  # 0x01 0x03 region_id suffix

RAFT_LOG_SUFFIX = b"\x01"
RAFT_STATE_SUFFIX = b"\x02"
APPLY_STATE_SUFFIX = b"\x03"
SNAPSHOT_RAFT_STATE_SUFFIX = b"\x04"
REGION_STATE_SUFFIX = b"\x01"


def data_key(key: bytes) -> bytes:
    return DATA_PREFIX + key


def origin_key(data_key_: bytes) -> bytes:
    if not data_key_.startswith(DATA_PREFIX):
        raise ValueError(f"invalid data key {data_key_!r}")
    return data_key_[len(DATA_PREFIX) :]


def data_end_key(region_end_key: bytes) -> bytes:
    """Region end key '' means +inf: map to the end of the data range."""
    if not region_end_key:
        return DATA_MAX_KEY
    return data_key(region_end_key)


def region_raft_prefix(region_id: int) -> bytes:
    return LOCAL_PREFIX + REGION_RAFT_PREFIX + encode_u64(region_id)


def raft_log_key(region_id: int, log_index: int) -> bytes:
    return region_raft_prefix(region_id) + RAFT_LOG_SUFFIX + encode_u64(log_index)


def raft_state_key(region_id: int) -> bytes:
    return region_raft_prefix(region_id) + RAFT_STATE_SUFFIX


def apply_state_key(region_id: int) -> bytes:
    return region_raft_prefix(region_id) + APPLY_STATE_SUFFIX


def region_meta_prefix(region_id: int) -> bytes:
    return LOCAL_PREFIX + REGION_META_PREFIX + encode_u64(region_id)


def region_state_key(region_id: int) -> bytes:
    return region_meta_prefix(region_id) + REGION_STATE_SUFFIX


def raft_log_index(key: bytes) -> int:
    expect = 2 + 8 + 1 + 8  # prefixes + region id + suffix + index
    if len(key) != expect:
        raise ValueError(f"invalid raft log key {key!r}")
    return decode_u64(key, 11)
