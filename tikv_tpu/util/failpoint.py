"""Failpoints: deterministic fault injection for tests.

Re-expression of the ``fail`` crate the reference leans on (179 fail_point!
sites; tests/failpoints/cases/): named points compiled into the code are
no-ops until a test configures an action —

    "off"          do nothing (default)
    "return"       make the site raise FailpointError (callers see a fault)
    "panic"        raise RuntimeError (unrecoverable-path testing)
    "pause"        block until the point is reconfigured (race windows)
    "sleep(ms)"    delay the thread
    "N*action"     apply the action only N times, then off (pause excepted:
                   a pause ends only when reconfigured, so counts never
                   decrement it)

``fail_point("name")`` at a call site; ``cfg()/remove()/teardown()`` from
tests (also honors the FAILPOINTS env var, "name=action;name2=action").
"""

from __future__ import annotations

import os
import threading
import time


class FailpointError(Exception):
    def __init__(self, name: str):
        self.name = name
        super().__init__(f"failpoint {name!r} triggered")


_mu = threading.Condition()
_actions: dict[str, tuple[str, int | None]] = {}  # name -> (action, remaining)


def _load_env() -> None:
    spec = os.environ.get("FAILPOINTS", "")
    for part in spec.split(";"):
        if "=" in part:
            name, action = part.split("=", 1)
            cfg(name.strip(), action.strip())


def cfg(name: str, action: str) -> None:
    """Configure a failpoint: e.g. cfg("apply_before_write", "return") or
    cfg("snap_gen", "2*return")."""
    count: int | None = None
    if "*" in action:
        n, action = action.split("*", 1)
        count = int(n)
    with _mu:
        if action == "off":
            _actions.pop(name, None)
        else:
            _actions[name] = (action, count)
        _mu.notify_all()


def remove(name: str) -> None:
    cfg(name, "off")


def teardown() -> None:
    with _mu:
        _actions.clear()
        _mu.notify_all()


def list_active() -> dict[str, str]:
    """Active points, counted actions rendered with their REMAINING count
    ("2*return" decays to "1*return" after one trigger) so tests can see how
    far an injection schedule has progressed."""
    with _mu:
        return {
            n: (a if c is None else f"{c}*{a}") for n, (a, c) in _actions.items()
        }


def fail_point(name: str) -> None:
    """The injected call site. No-op unless the point is configured."""
    if not _actions:
        # disabled fast path: hot call sites (apply loop, scheduler,
        # coprocessor entry) must not contend on _mu when nothing is
        # configured — a bare dict-truthiness read is atomic under the GIL
        return
    with _mu:
        ent = _actions.get(name)
        if ent is None:
            return
        action, count = ent
        if action == "pause":
            # a pause window ends when the point is reconfigured (cfg/remove
            # replaces the entry), so counts never decrement it — every
            # arriving thread blocks until release
            while True:
                cur = _actions.get(name)
                if cur is None or cur[0] != "pause":
                    return
                # plain wait: cfg()/remove()/teardown() notify_all on every
                # reconfiguration, so paused threads wake exactly when the
                # window closes instead of polling at 10ms granularity
                _mu.wait()
        if count is not None:
            if count <= 1:
                _actions.pop(name, None)
            else:
                _actions[name] = (action, count - 1)
    if action == "return":
        raise FailpointError(name)
    if action == "panic":
        raise RuntimeError(f"failpoint panic: {name}")
    if action.startswith("sleep("):
        time.sleep(float(action[6:-1]) / 1000.0)
        return
    raise ValueError(f"unknown failpoint action {action!r}")


_load_env()
