"""Version-gated feature rollout (components/pd_client/src/feature_gate.rs:14).

PD tracks the CLUSTER version — the minimum version across stores during a
rolling upgrade — and every store's FeatureGate follows it monotonically.
A feature turns on only once the whole cluster passes its required version,
so mixed-version clusters never run protocol the oldest member can't speak.

This framework's gated features are its device-serving surfaces: single-chip
coprocessor execution, multi-device mesh serving, and fused batch serving —
each may be further toggled at runtime through POST /config (the online
reconfiguration path), but the gate is the hard floor.
"""

from __future__ import annotations

import threading


def _ver_to_val(major: int, minor: int, patch: int) -> int:
    # feature_gate.rs:9 ver_to_val: u16 fields packed into one comparable int
    return (major << 32) | (minor << 16) | patch


def parse_version(version: str) -> int:
    """'5.1.0' (optionally with a -suffix or leading v) → comparable value."""
    v = version.strip().lstrip("v")
    core = v.split("-", 1)[0].split("+", 1)[0]
    parts = core.split(".")
    if len(parts) != 3:
        raise ValueError(f"not a semver triple: {version!r}")
    major, minor, patch = (int(p) for p in parts)
    if not all(0 <= x < 1 << 16 for x in (major, minor, patch)):
        raise ValueError(f"version component out of range: {version!r}")
    return _ver_to_val(major, minor, patch)


class Feature:
    """A capability requiring a minimum cluster version (feature_gate.rs:56)."""

    __slots__ = ("ver", "name")

    def __init__(self, major: int, minor: int, patch: int, name: str = ""):
        self.ver = _ver_to_val(major, minor, patch)
        self.name = name


# The framework's own gated features.  Versions follow this project's
# release line: device serving shipped in 5.0, mesh + fused batches in 5.1.
DEVICE_COPROCESSOR = Feature(5, 0, 0, "device-coprocessor")
MESH_SERVING = Feature(5, 1, 0, "mesh-serving")
BATCH_FUSION = Feature(5, 1, 0, "batch-fusion")

RESOLVED_TS_CHECK_LEADER = Feature(5, 0, 0, "resolved-ts-check-leader")


class FeatureGate:
    """Monotonic cluster-version latch (feature_gate.rs:14).

    ``set_version`` only ever raises the stored version — a stale heartbeat
    from a lagging PD follower must not re-disable features — and returns
    True when it actually advanced, mirroring the reference's CAS loop.
    """

    def __init__(self, version: str | None = None):
        self._val = 0
        self._mu = threading.Lock()
        if version:
            self.set_version(version)

    def set_version(self, version: str) -> bool:
        val = parse_version(version)
        with self._mu:
            if val <= self._val:
                return False
            self._val = val
            return True

    def can_enable(self, feature: Feature) -> bool:
        with self._mu:
            return self._val >= feature.ver

    def version_value(self) -> int:
        with self._mu:
            return self._val
