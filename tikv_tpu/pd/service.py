"""PD over the wire: the placement driver as a TCP service.

Re-expression of the PD gRPC surface that ``components/pd_client`` consumes
(src/lib.rs:87 bootstrap, :147 get_region, :180 region_heartbeat, :208
ask_batch_split, :217 store_heartbeat, :255 get_tso) plus the address book
(``src/server/resolve.rs``: store id -> socket addr resolves through PD's
store records).  ``PdService`` exposes an in-process ``MockPd`` behind the
framed-TCP server; ``RemotePd`` is the ``PdClient`` implementation store
processes use — together they let a cluster span real OS processes.
"""

from __future__ import annotations

import threading

from ..raft.region import Region
from ..raft.store import decode_region, encode_region
from .client import MockPd, PdClient


class PdService:
    """Dispatch-compatible wrapper (server.Server speaks to anything with a
    ``dispatch``).  Only pd_-prefixed methods are reachable."""

    def __init__(self, pd: MockPd):
        self.pd = pd

    def dispatch(self, method: str, req: dict):
        if not method.startswith("pd_"):
            return {"error": {"other": f"unknown method {method}"}}
        handler = getattr(self, method, None)
        if handler is None:
            return {"error": {"other": f"unknown method {method}"}}
        try:
            return handler(req)
        except Exception as e:  # noqa: BLE001 — wire boundary
            return {"error": {"other": repr(e)}}

    def pd_alloc_id(self, req: dict) -> dict:
        return {"id": self.pd.alloc_id()}

    def pd_get_tso(self, req: dict) -> dict:
        return {"ts": self.pd.get_tso()}

    def pd_bootstrap_region(self, req: dict) -> dict:
        region, _ = decode_region(req["region"])
        # first-wins: concurrent bootstrappers race benignly
        if self.pd.get_region_by_id(region.id) is None:
            self.pd.bootstrap_region(region)
            return {"bootstrapped": True}
        return {"bootstrapped": False}

    def pd_get_region_by_key(self, req: dict) -> dict:
        r = self.pd.get_region_by_key(req["key"])
        return {"region": encode_region(r) if r else None}

    def pd_get_region_by_id(self, req: dict) -> dict:
        r = self.pd.get_region_by_id(req["region_id"])
        leader = self.pd.leader_of(req["region_id"]) if r else None
        return {"region": encode_region(r) if r else None, "leader_store": leader}

    def pd_region_heartbeat(self, req: dict) -> dict:
        region, _ = decode_region(req["region"])
        op = self.pd.region_heartbeat(region, req["leader_store"],
                                      load=req.get("load", 0))
        return {"operator": op}

    def pd_store_heartbeat(self, req: dict) -> dict:
        status = self.pd.store_heartbeat(req["store_id"], req.get("stats", {}))
        return {"replication": status}

    def pd_report_split(self, req: dict) -> dict:
        left, _ = decode_region(req["left"])
        right, _ = decode_region(req["right"])
        self.pd.report_split(left, right)
        return {}

    def pd_put_store(self, req: dict) -> dict:
        self.pd.put_store(req["store_id"], addr=tuple(req["addr"]) if req.get("addr") else None)
        return {}

    def pd_get_store_addr(self, req: dict) -> dict:
        addr = self.pd.get_store_addr(req["store_id"])
        return {"addr": list(addr) if addr else None}

    def pd_alive_stores(self, req: dict) -> dict:
        return {"stores": self.pd.alive_stores(req.get("within_secs", 30.0))}

    def pd_update_gc_safe_point(self, req: dict) -> dict:
        self.pd.update_gc_safe_point(req["ts"])
        return {}

    def pd_get_gc_safe_point(self, req: dict) -> dict:
        return {"ts": self.pd.get_gc_safe_point()}

    def pd_get_cluster_version(self, req: dict) -> dict:
        return {"version": self.pd.get_cluster_version()}

    def pd_set_cluster_version(self, req: dict) -> dict:
        self.pd.set_cluster_version(req["version"])
        return {"ok": True}

    def pd_add_operator(self, req: dict) -> dict:
        self.pd.add_operator(req["region_id"], req["operator"])
        return {}

    def pd_advertise_device_regions(self, req: dict) -> dict:
        owners = self.pd.advertise_device_regions(
            req["store_id"], req.get("regions") or ())
        return {"owners": owners}


class RemotePd(PdClient):
    """PdClient over the wire (pd_client's RpcClient with reconnect,
    util.rs): one multiplexed connection, re-dialed on failure."""

    def __init__(self, host: str, port: int, security=None):
        self.addr = (host, port)
        self.security = security
        self._mu = threading.Lock()
        self._client = None

    def _call(self, method: str, req: dict) -> dict:
        from ..server.server import Client

        last: Exception | None = None
        for attempt in (0, 1):
            try:
                # dial outside the mutex: a slow connect must not block every
                # concurrent PD caller, and a refused dial is as retryable as
                # a broken call (pd_client reconnect, util.rs)
                with self._mu:
                    client = self._client
                if client is None:
                    client = Client(*self.addr, security=self.security)
                    with self._mu:
                        if self._client is None:
                            self._client = client
                        elif self._client is not client:
                            client.close()
                            client = self._client
                resp = client.call(method, req, timeout=10.0)
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                with self._mu:
                    if self._client is not None:
                        self._client.close()
                        self._client = None
                continue
            if isinstance(resp, dict) and "error" in resp:
                raise RuntimeError(f"pd {method}: {resp['error']}")
            return resp
        raise ConnectionError(f"pd {method} unreachable: {last!r}")

    def get_cluster_version(self) -> str:
        return self._call("pd_get_cluster_version", {})["version"]

    def set_cluster_version(self, version: str) -> None:
        self._call("pd_set_cluster_version", {"version": version})

    def alloc_id(self) -> int:
        return self._call("pd_alloc_id", {})["id"]

    def get_tso(self) -> int:
        return self._call("pd_get_tso", {})["ts"]

    def bootstrap_region(self, region: Region) -> bool:
        return self._call("pd_bootstrap_region", {"region": encode_region(region)})["bootstrapped"]

    def get_region_by_key(self, key: bytes) -> Region | None:
        raw = self._call("pd_get_region_by_key", {"key": key})["region"]
        return decode_region(raw)[0] if raw else None

    def get_region_by_id(self, region_id: int) -> Region | None:
        raw = self._call("pd_get_region_by_id", {"region_id": region_id})["region"]
        return decode_region(raw)[0] if raw else None

    def leader_of(self, region_id: int) -> int | None:
        return self._call("pd_get_region_by_id", {"region_id": region_id})["leader_store"]

    def region_heartbeat(self, region: Region, leader_store: int,
                         load: float = 0.0) -> dict | None:
        r = self._call(
            "pd_region_heartbeat",
            {"region": encode_region(region), "leader_store": leader_store,
             "load": load},
        )
        return r.get("operator")

    def store_heartbeat(self, store_id: int, stats: dict):
        r = self._call("pd_store_heartbeat", {"store_id": store_id, "stats": stats})
        return r.get("replication") if isinstance(r, dict) else None

    def report_split(self, left: Region, right: Region) -> None:
        self._call(
            "pd_report_split",
            {"left": encode_region(left), "right": encode_region(right)},
        )

    def put_store(self, store_id: int, addr: tuple[str, int] | None = None) -> None:
        self._call(
            "pd_put_store",
            {"store_id": store_id, "addr": list(addr) if addr else None},
        )

    def get_store_addr(self, store_id: int) -> tuple[str, int] | None:
        raw = self._call("pd_get_store_addr", {"store_id": store_id})["addr"]
        return (raw[0], raw[1]) if raw else None

    def alive_stores(self, within_secs: float = 30.0) -> list[int]:
        return self._call("pd_alive_stores", {"within_secs": within_secs})["stores"]

    def update_gc_safe_point(self, ts: int) -> None:
        self._call("pd_update_gc_safe_point", {"ts": ts})

    def advertise_device_regions(self, store_id: int, region_ids) -> dict[int, int]:
        r = self._call("pd_advertise_device_regions",
                       {"store_id": store_id, "regions": list(region_ids)})
        owners = r.get("owners") if isinstance(r, dict) else None
        return owners if isinstance(owners, dict) else {}

    def add_operator(self, region_id: int, op: dict) -> None:
        self._call("pd_add_operator", {"region_id": region_id, "operator": op})

    def get_gc_safe_point(self) -> int:
        return self._call("pd_get_gc_safe_point", {})["ts"]

    def close(self) -> None:
        with self._mu:
            if self._client is not None:
                self._client.close()
                self._client = None
