"""Placement driver (PD) client trait + in-process PD implementation.

Re-expression of ``components/pd_client`` (``src/lib.rs:73``: bootstrap,
get_region, region_heartbeat, ask_batch_split, store_heartbeat, get_tso) and
``components/test_pd``'s in-process mock.  The in-process PD is authoritative
for: id allocation, TSO, region routing metadata, store liveness, and split
scheduling decisions (max region size → ask_split).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..raft.region import Region
from ..storage.txn_types import compose_ts


class PdClient:
    """The trait surface node/raftstore/GC code programs against."""

    def alloc_id(self) -> int: ...

    def get_tso(self) -> int: ...

    def bootstrap_region(self, region: Region) -> None: ...

    def get_region_by_key(self, key: bytes) -> Region | None: ...

    def get_region_by_id(self, region_id: int) -> Region | None: ...

    def region_heartbeat(self, region: Region, leader_store: int,
                         load: float = 0.0) -> dict | None:
        """Returns at most one scheduling operator for the leader to run."""
        ...

    def store_heartbeat(self, store_id: int, stats: dict) -> None: ...

    def report_split(self, left: Region, right: Region) -> None: ...

    def get_gc_safe_point(self) -> int: ...

    def get_cluster_version(self) -> str:
        return "0.0.0"


@dataclass
class StoreInfo:
    store_id: int
    last_heartbeat: float = 0.0
    stats: dict = field(default_factory=dict)
    addr: tuple | None = None  # (host, port) — the resolve.rs address book


class MockPd(PdClient):
    """In-process PD: single authority, thread-safe (test_pd's TestPdClient)."""

    def __init__(self, start_physical_ms: int | None = None):
        self._mu = threading.RLock()
        self._next_id = 1000
        self._logical = 0
        self._physical = start_physical_ms or int(time.time() * 1000)
        self.regions: dict[int, Region] = {}
        self.leaders: dict[int, int] = {}
        self.stores: dict[int, StoreInfo] = {}
        self.gc_safe_point = 0
        self.max_region_keys: int | None = None  # split trigger for heartbeats
        self.split_requests: list[int] = []
        # scheduling (pd-server schedulers): None disables every policy
        self.replication_factor: int | None = None
        self.balance_threshold = 2
        # balance-region: move a replica when the most-loaded voter store
        # hosts this many more replicas than the least-loaded spare store
        self.balance_region_threshold = 4
        self.operator_ttl = 30.0
        self.store_down_secs = 10.0
        self.operators: dict[int, dict] = {}  # region_id -> pending operator
        # per-region leader write-load EWMA (pd-server hot-region statistics)
        self.region_load: dict[int, float] = {}
        # one leader-balance weight unit per this many load units: blends
        # counts with load (load 0 everywhere == pure count balance)
        self.load_weight_unit = 100.0
        # cluster version driving FeatureGate rollout (feature_gate.rs:14);
        # rolling upgrades raise it once every store runs the new release
        self.cluster_version = "5.1.0"
        # cluster replication status (replication_mode.rs ReplicationStatus)
        self.replication: dict = {"mode": "majority", "state": "sync", "labels": {}}
        self._groups_alive_since: dict = {}
        # in-flight replica moves: region_id -> [src, dst, deadline, done_at]
        # done_at None while the move runs; set when remove_peer was issued,
        # after which the entry LINGERS so its influence keeps adjusting
        # load estimates until region heartbeats catch up (the reference
        # PD's operator-influence accounting)
        self._moves: dict[int, list] = {}
        self._move_linger = 10.0
        # device-owner placement (docs/wire_path.md): region_id -> the store
        # whose region column cache holds a warm device-resident image.
        # Stores advertise their warm set each heartbeat; the full map rides
        # back so every store can forward device-eligible DAGs to the owner
        self.device_owners: dict[int, int] = {}

    # -- ids / tso ---------------------------------------------------------

    def alloc_id(self) -> int:
        with self._mu:
            self._next_id += 1
            return self._next_id

    def get_tso(self) -> int:
        with self._mu:
            now_ms = int(time.time() * 1000)
            if now_ms > self._physical:
                self._physical = now_ms
                self._logical = 0
            self._logical += 1
            return compose_ts(self._physical, self._logical)

    # -- region metadata ---------------------------------------------------

    def bootstrap_region(self, region: Region) -> None:
        with self._mu:
            self.regions[region.id] = region

    def get_region_by_key(self, key: bytes) -> Region | None:
        with self._mu:
            for r in self.regions.values():
                if r.contains(key):
                    return r.clone()
        return None

    def get_region_by_id(self, region_id: int) -> Region | None:
        with self._mu:
            r = self.regions.get(region_id)
            return r.clone() if r else None

    def leader_of(self, region_id: int) -> int | None:
        with self._mu:
            return self.leaders.get(region_id)

    def region_heartbeat(self, region: Region, leader_store: int,
                         load: float = 0.0) -> dict | None:
        """Record the heartbeat and answer with at most ONE operator (the
        reference's heartbeat-response scheduling, pd_client lib.rs:180 —
        PD drives the cluster by piggybacking add/remove-peer and
        transfer-leader orders on region heartbeat responses).  ``load`` is
        the leader's write ops since its last beat; an EWMA of it weights
        the leader-balance scheduler (pd-server's hot-region awareness) so
        one store leading all the hot regions counts as imbalanced even at
        equal leader counts."""
        with self._mu:
            prev = self.region_load.get(region.id, 0.0)
            self.region_load[region.id] = 0.5 * prev + 0.5 * float(load)
            cur = self.regions.get(region.id)
            if cur is None or (
                (region.epoch.version, region.epoch.conf_ver)
                >= (cur.epoch.version, cur.epoch.conf_ver)
            ):
                self.regions[region.id] = region.clone()
                self.leaders[region.id] = leader_store
            # only the CURRENT leader consumes operators: a just-deposed
            # ex-leader's heartbeat must not pop (and lose) one it cannot run
            if self.leaders.get(region.id) == leader_store:
                pending = self.operators.pop(region.id, None)
                if pending is not None:
                    return pending
            return self._schedule(region, leader_store)

    # -- scheduling policies (the pd-server scheduler equivalents) ----------

    def add_operator(self, region_id: int, op: dict) -> None:
        """Manual operator injection (pd-ctl operator add ...)."""
        with self._mu:
            self.operators[region_id] = op

    def _schedule(self, region: Region, leader_store: int) -> dict | None:
        """Called under self._mu.  Policies, in priority order:
        1. replica repair — fewer voters than replication_factor and a spare
           alive store exists -> add_peer
        2. excess replica  — more voters than replication_factor ->
           remove_peer (never the leader's)
        3. leader balance  — this store leads >= balance_threshold more
           regions than the least-loaded peer store -> transfer_leader
        All disabled while replication_factor is None."""
        if self.replication_factor is None:
            return None
        now = time.time()
        alive = {
            s.store_id
            for s in self.stores.values()
            if now - s.last_heartbeat < self.store_down_secs
        }
        voters = [p for p in region.peers if p.role == "voter"]
        hosting = {p.store_id for p in region.peers}
        mv = self._moves.get(region.id)
        if mv is not None and mv[3] is None:
            # an ACTIVE balance move owns this region's scheduling: the
            # generic excess-replica rule below must not fire mid-move (it
            # could remove the replica the move just added).  A lingering
            # completed move only contributes influence — repair and leader
            # balance keep running for the region.
            return self._balance_region(region, leader_store, alive, now)
        if len(voters) < self.replication_factor:
            spare = sorted(alive - hosting)
            if spare:
                return {"type": "add_peer", "store_id": spare[0]}
        # a voter on a permanently-down store must be REPLACED even when the
        # count still equals the factor (the reference removes down peers
        # after max-store-down-time, which then triggers the add path) —
        # but only while the live voters alone can still form quorum
        dead_voters = [p for p in voters if p.store_id not in alive]
        live_voters = len(voters) - len(dead_voters)
        if dead_voters and len(voters) == self.replication_factor and live_voters > len(voters) // 2:
            return {"type": "remove_peer", "peer_id": dead_voters[0].peer_id}
        if len(voters) > self.replication_factor and region.id not in self._moves:
            # (a lingering move means the extra replica is already being
            # removed — firing here could target the WRONG peer off a stale
            # region view)
            # prefer dropping replicas on dead stores, then non-leaders
            dead = [p for p in voters if p.store_id not in alive]
            candidates = dead or [p for p in voters if p.store_id != leader_store]
            if candidates:
                return {"type": "remove_peer", "peer_id": candidates[0].peer_id}
        # balance-region (the pd-server balance-region scheduler,
        # pd_client lib.rs:180-217 operator surface): two-phase replica move
        # tracked in self._moves — add_peer on the target first, then once
        # the target is a voter, remove_peer on the source; expired moves
        # are abandoned (operator TTL), so a wedged conf change can't pin
        # the region forever
        op = self._balance_region(region, leader_store, alive, now)
        if op is not None:
            return op
        # leader balance over the stores hosting this region: each led
        # region weighs 1 + load_ewma/unit, so equal COUNTS still rebalance
        # when one store leads all the hot regions (and with no load
        # reported the weights reduce to plain counts — the old behavior)
        weights = {sid: 0.0 for sid in alive}
        for rid, lsid in self.leaders.items():
            if lsid in weights:
                weights[lsid] += 1.0 + self.region_load.get(rid, 0.0) / self.load_weight_unit
        peer_stores = [p.store_id for p in voters if p.store_id in alive and p.store_id != leader_store]
        if peer_stores and leader_store in weights:
            target = min(peer_stores, key=lambda s: weights[s])
            w_region = 1.0 + self.region_load.get(region.id, 0.0) / self.load_weight_unit
            delta = weights[leader_store] - weights[target]
            # transferring THIS region moves w_region across, changing the
            # delta to delta − 2·w_region: fire only when that IMPROVES
            # balance (delta > w_region ⇒ |delta − 2w| < delta), or a hot
            # region ping-pongs — each transfer overshoots the imbalance the
            # other way and immediately re-triggers in reverse
            if delta >= self.balance_threshold and delta > w_region:
                tp = region.peer_on_store(target)
                return {"type": "transfer_leader", "peer_id": tp.peer_id, "store_id": target}
        return None

    def _store_load(self, sid: int, replica_counts: dict[int, int]) -> tuple:
        """Ordering key for balance decisions: replica count first, reported
        used bytes as the size-weighted tiebreak (store_heartbeat stats)."""
        info = self.stores.get(sid)
        used = (info.stats or {}).get("used_bytes", 0) if info else 0
        return (replica_counts.get(sid, 0), used)

    def _gc_moves(self, now: float) -> None:
        for rid in list(self._moves):
            src, dst, deadline, done_at = self._moves[rid]
            if (done_at is None and now > deadline) or \
                    (done_at is not None and now - done_at > self._move_linger):
                del self._moves[rid]

    def _balance_region(self, region: Region, leader_store: int,
                        alive: set, now: float) -> dict | None:
        self._gc_moves(now)
        voters = [p for p in region.peers if p.role == "voter"]
        hosting = {p.store_id for p in region.peers}
        # phase 2 / retry of an in-flight move for THIS region
        mv = self._moves.get(region.id)
        if mv is not None and mv[3] is None:
            src, dst, _deadline, _done = mv
            if src not in hosting:
                mv[3] = now  # source already gone: done, linger
                return None
            dstp = region.peer_on_store(dst)
            if dstp is None:
                # add not applied yet (or lost): re-issue
                return {"type": "add_peer", "store_id": dst}
            if dstp.role != "voter":
                return None  # learner still catching up
            srcp = region.peer_on_store(src)
            if srcp is not None and src == leader_store:
                # can't remove the leader's replica: move leadership off
                return {"type": "transfer_leader", "peer_id": dstp.peer_id,
                        "store_id": dst}
            mv[3] = now  # linger for influence until heartbeats catch up
            if srcp is not None:
                return {"type": "remove_peer", "peer_id": srcp.peer_id}
            return None
        if mv is not None:
            return None  # completed move lingering: no new decisions here
        # phase 1: trigger a move when this region's most loaded voter
        # store dwarfs the least loaded spare store.  One move at a time —
        # every pending/lingering move's influence is folded into the load
        # estimate, so stale heartbeat views can't trigger a stampede.
        if any(m[3] is None for m in self._moves.values()):
            return None
        if len(voters) != self.replication_factor:
            return None  # repair rules own abnormal replica counts
        replica_counts: dict[int, int] = {sid: 0 for sid in alive}
        for r in self.regions.values():
            for p in r.peers:
                if p.store_id in replica_counts:
                    replica_counts[p.store_id] += 1
        for rid, (src, dst, _dl, _done) in self._moves.items():
            view = self.regions.get(rid)
            if view is not None and view.peer_on_store(src) is not None \
                    and src in replica_counts:
                replica_counts[src] -= 1  # removal decided, view stale
            if (view is None or view.peer_on_store(dst) is None) \
                    and dst in replica_counts:
                replica_counts[dst] += 1  # addition decided, view stale
        spare = sorted(alive - hosting)
        live_voter_sids = [p.store_id for p in voters if p.store_id in alive]
        if not spare or not live_voter_sids:
            return None
        src = max(live_voter_sids, key=lambda s: self._store_load(s, replica_counts))
        dst = min(spare, key=lambda s: self._store_load(s, replica_counts))
        if replica_counts.get(src, 0) - replica_counts.get(dst, 0) < self.balance_region_threshold:
            return None
        self._moves[region.id] = [src, dst, now + self.operator_ttl, None]
        return {"type": "add_peer", "store_id": dst}

    def report_split(self, left: Region, right: Region) -> None:
        with self._mu:
            self.regions[left.id] = left.clone()
            self.regions[right.id] = right.clone()

    # -- stores ------------------------------------------------------------

    def put_store(self, store_id: int, addr: tuple | None = None) -> None:
        with self._mu:
            info = self.stores.get(store_id)
            if info is None:
                self.stores[store_id] = StoreInfo(store_id, addr=addr)
            elif addr is not None:
                info.addr = addr

    def get_store_addr(self, store_id: int) -> tuple | None:
        with self._mu:
            info = self.stores.get(store_id)
            return info.addr if info else None

    def advertise_device_regions(self, store_id: int, region_ids) -> dict[int, int]:
        """One store's current warm device-image placement (heartbeat
        cadence): replaces every entry previously owned by ``store_id`` with
        the advertised set and returns the WHOLE cluster map, so the caller
        refreshes its owner route cache in the same round trip.  Ownership
        conflicts resolve latest-writer-wins — a stale claim costs one
        forwarded hop that still returns correct (CPU-served) bytes."""
        rids = {int(r) for r in region_ids}
        with self._mu:
            for rid in [r for r, s in self.device_owners.items()
                        if s == store_id and r not in rids]:
                del self.device_owners[rid]
            for rid in rids:
                self.device_owners[rid] = store_id
            return dict(self.device_owners)

    def store_heartbeat(self, store_id: int, stats: dict) -> dict:
        """Record liveness + stats; returns the cluster replication status
        (pd.rs store heartbeat response carries ReplicationStatus)."""
        with self._mu:
            info = self.stores.setdefault(store_id, StoreInfo(store_id))
            info.last_heartbeat = time.time()
            info.stats = stats
            self._update_replication_state()
            return dict(self.replication)

    # -- replication mode (DR auto-sync) ------------------------------------

    def enable_dr_auto_sync(self, labels: dict[int, str]) -> None:
        """Switch to DrAutoSync (replication_mode.rs): ``labels`` maps
        store_id -> label group (e.g. availability zone).  Commit then
        requires every group to hold the entry while state is ``sync``."""
        with self._mu:
            self.replication = {
                "mode": "dr_auto_sync",
                "state": "sync",
                "labels": dict(labels),
            }
            self._groups_alive_since: dict = {}

    def _update_replication_state(self) -> None:
        """The DR state machine (caller holds _mu): a label group losing all
        its stores drops the cluster to ``async`` (majority-only commit —
        availability over cross-DC integrity); when the group returns, the
        cluster passes through ``sync_recover`` until every group has been
        continuously alive for a grace period, then re-enters ``sync``."""
        rep = self.replication
        if rep.get("mode") != "dr_auto_sync":
            return
        now = time.time()
        labels = rep["labels"]
        alive = {
            s.store_id for s in self.stores.values()
            if now - s.last_heartbeat < self.store_down_secs
        }
        group_alive: dict[str, bool] = {}
        for sid, g in labels.items():
            group_alive[g] = group_alive.get(g, False) or sid in alive
        if not all(group_alive.values()):
            rep["state"] = "async"
            self._groups_alive_since = {}
            return
        if rep["state"] == "async":
            rep["state"] = "sync_recover"
            self._groups_alive_since = {"t": now}
        if rep["state"] == "sync_recover":
            # grace: one liveness window with every group healthy
            if now - self._groups_alive_since.get("t", now) >= min(
                    2.0, self.store_down_secs / 2):
                rep["state"] = "sync"

    def alive_stores(self, within_secs: float = 30.0) -> list[int]:
        now = time.time()
        with self._mu:
            return [s.store_id for s in self.stores.values() if now - s.last_heartbeat < within_secs]

    # -- gc ----------------------------------------------------------------

    def get_cluster_version(self) -> str:
        return self.cluster_version

    def set_cluster_version(self, version: str) -> None:
        from .feature_gate import parse_version

        # monotonic, like every consumer gate: a downgrade request is a bug
        if parse_version(version) < parse_version(self.cluster_version):
            raise ValueError(f"cluster version cannot decrease to {version}")
        self.cluster_version = version

    def update_gc_safe_point(self, ts: int) -> None:
        with self._mu:
            self.gc_safe_point = max(self.gc_safe_point, ts)

    def get_gc_safe_point(self) -> int:
        with self._mu:
            return self.gc_safe_point
