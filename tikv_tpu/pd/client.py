"""Placement driver (PD) client trait + in-process PD implementation.

Re-expression of ``components/pd_client`` (``src/lib.rs:73``: bootstrap,
get_region, region_heartbeat, ask_batch_split, store_heartbeat, get_tso) and
``components/test_pd``'s in-process mock.  The in-process PD is authoritative
for: id allocation, TSO, region routing metadata, store liveness, and split
scheduling decisions (max region size → ask_split).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..raft.region import Region
from ..storage.txn_types import compose_ts


class PdClient:
    """The trait surface node/raftstore/GC code programs against."""

    def alloc_id(self) -> int: ...

    def get_tso(self) -> int: ...

    def bootstrap_region(self, region: Region) -> None: ...

    def get_region_by_key(self, key: bytes) -> Region | None: ...

    def get_region_by_id(self, region_id: int) -> Region | None: ...

    def region_heartbeat(self, region: Region, leader_store: int) -> None: ...

    def store_heartbeat(self, store_id: int, stats: dict) -> None: ...

    def report_split(self, left: Region, right: Region) -> None: ...

    def get_gc_safe_point(self) -> int: ...


@dataclass
class StoreInfo:
    store_id: int
    last_heartbeat: float = 0.0
    stats: dict = field(default_factory=dict)
    addr: tuple | None = None  # (host, port) — the resolve.rs address book


class MockPd(PdClient):
    """In-process PD: single authority, thread-safe (test_pd's TestPdClient)."""

    def __init__(self, start_physical_ms: int | None = None):
        self._mu = threading.RLock()
        self._next_id = 1000
        self._logical = 0
        self._physical = start_physical_ms or int(time.time() * 1000)
        self.regions: dict[int, Region] = {}
        self.leaders: dict[int, int] = {}
        self.stores: dict[int, StoreInfo] = {}
        self.gc_safe_point = 0
        self.max_region_keys: int | None = None  # split trigger for heartbeats
        self.split_requests: list[int] = []

    # -- ids / tso ---------------------------------------------------------

    def alloc_id(self) -> int:
        with self._mu:
            self._next_id += 1
            return self._next_id

    def get_tso(self) -> int:
        with self._mu:
            now_ms = int(time.time() * 1000)
            if now_ms > self._physical:
                self._physical = now_ms
                self._logical = 0
            self._logical += 1
            return compose_ts(self._physical, self._logical)

    # -- region metadata ---------------------------------------------------

    def bootstrap_region(self, region: Region) -> None:
        with self._mu:
            self.regions[region.id] = region

    def get_region_by_key(self, key: bytes) -> Region | None:
        with self._mu:
            for r in self.regions.values():
                if r.contains(key):
                    return r.clone()
        return None

    def get_region_by_id(self, region_id: int) -> Region | None:
        with self._mu:
            r = self.regions.get(region_id)
            return r.clone() if r else None

    def leader_of(self, region_id: int) -> int | None:
        with self._mu:
            return self.leaders.get(region_id)

    def region_heartbeat(self, region: Region, leader_store: int) -> None:
        with self._mu:
            cur = self.regions.get(region.id)
            if cur is None or (
                (region.epoch.version, region.epoch.conf_ver)
                >= (cur.epoch.version, cur.epoch.conf_ver)
            ):
                self.regions[region.id] = region.clone()
                self.leaders[region.id] = leader_store

    def report_split(self, left: Region, right: Region) -> None:
        with self._mu:
            self.regions[left.id] = left.clone()
            self.regions[right.id] = right.clone()

    # -- stores ------------------------------------------------------------

    def put_store(self, store_id: int, addr: tuple | None = None) -> None:
        with self._mu:
            info = self.stores.get(store_id)
            if info is None:
                self.stores[store_id] = StoreInfo(store_id, addr=addr)
            elif addr is not None:
                info.addr = addr

    def get_store_addr(self, store_id: int) -> tuple | None:
        with self._mu:
            info = self.stores.get(store_id)
            return info.addr if info else None

    def store_heartbeat(self, store_id: int, stats: dict) -> None:
        with self._mu:
            info = self.stores.setdefault(store_id, StoreInfo(store_id))
            info.last_heartbeat = time.time()
            info.stats = stats

    def alive_stores(self, within_secs: float = 30.0) -> list[int]:
        now = time.time()
        with self._mu:
            return [s.store_id for s in self.stores.values() if now - s.last_heartbeat < within_secs]

    # -- gc ----------------------------------------------------------------

    def update_gc_safe_point(self, ts: int) -> None:
        with self._mu:
            self.gc_safe_point = max(self.gc_safe_point, ts)

    def get_gc_safe_point(self) -> int:
        with self._mu:
            return self.gc_safe_point
