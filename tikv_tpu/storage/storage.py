"""The Storage façade — the transactional + raw KV surface of one store.

Re-expression of ``src/storage/mod.rs:121`` (``Storage<E, L>``): point/range
MVCC reads (get/batch_get/scan), txn commands via the scheduler
(``sched_txn_command`` :919), and the raw KV API with TTL and atomic CAS
(``mod.rs:997+``, ``raw/ttl.rs``, ``commands/{compare_and_swap,
atomic_store}.rs``).

Raw keys live in CF_DEFAULT under their own encoding (``r`` prefix keeps them
disjoint from txn data); TTL is an expiry timestamp suffix on the value,
filtered on read and purged by the GC worker's compaction pass.
"""

from __future__ import annotations

import time

from ..util import codec
from .concurrency_manager import ConcurrencyManager
from .engine import CF_DEFAULT, WriteBatch
from .kv import Engine, LocalEngine
from .mvcc import ForwardScanner, BackwardScanner, IsolationLevel, PointGetter, Statistics
from .txn.commands import Command
from .txn.latches import Latches
from .txn.scheduler import Scheduler
from .txn_types import Key

RAW_PREFIX = b"r"
_NO_TTL = 0xFFFFFFFFFFFFFFFF


def _raw_key(key: bytes) -> bytes:
    return RAW_PREFIX + key


def _encode_raw_value(value: bytes, ttl_secs: int, now: float) -> bytes:
    expire = _NO_TTL if ttl_secs == 0 else int(now) + ttl_secs
    return value + codec.encode_u64(expire)


def _stale_snap_ctx(ctx: dict | None, ts: int) -> dict | None:
    """Effective stale-read context for the engine snapshot: the MVCC read
    executes at ``ts``, so the watermark admission must cover ``ts`` even
    when the client declared a lower ``read_ts`` — otherwise a lagging
    replica admits a read whose MVCC pass then reads above the watermark
    and silently misses committed data (same clamp as the coprocessor's
    ``stale_read_ctx``, docs/stale_reads.md)."""
    if not ctx or not ctx.get("stale_read"):
        return ctx
    read_ts = ctx.get("read_ts")
    if read_ts is None or int(read_ts) < ts:
        ctx = dict(ctx, read_ts=ts)
    return ctx


def _decode_raw_value(stored: bytes, now: float) -> bytes | None:
    value, expire = stored[:-8], codec.decode_u64(stored, len(stored) - 8)
    if expire != _NO_TTL and expire <= int(now):
        return None
    return value, expire  # type: ignore[return-value]


class Storage:
    def __init__(self, engine: Engine | None = None,
                 concurrency_manager: ConcurrencyManager | None = None,
                 group_commit_max: int = 16, sched_pool_size: int = 4):
        self.engine = engine or LocalEngine()
        self.cm = concurrency_manager or ConcurrencyManager()
        # group_commit_max=1 disables write coalescing (docs/write_path.md):
        # every txn command then pays its own engine write / raft proposal
        self.scheduler = Scheduler(self.engine, self.cm,
                                   pool_size=sched_pool_size,
                                   group_commit_max=group_commit_max)
        self._raw_latches = Latches(64)

    @staticmethod
    def _observe_batch(op: str, n: int) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.histogram(
            "tikv_storage_batch_size",
            "Keys per batched storage call, by op",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        ).observe(n, op=op)

    # -- transactional reads ----------------------------------------------

    def get(
        self,
        key: bytes,
        ts: int,
        ctx: dict | None = None,
        isolation: IsolationLevel = IsolationLevel.SI,
        bypass_locks: frozenset[int] = frozenset(),
    ) -> bytes | None:
        k = Key.from_raw(key)
        self.cm.read_key_check(k, ts, bypass_locks)
        snap = self.engine.snapshot(_stale_snap_ctx(ctx, ts))
        return PointGetter(snap, ts, isolation, bypass_locks).get(k)

    def batch_get(self, keys: list[bytes], ts: int, ctx: dict | None = None, **kw) -> list[tuple[bytes, bytes]]:
        """One snapshot, ONE PointGetter, one pass (mod.rs:270 batch_get) —
        the old shape re-entered per key, building a fresh getter (fresh
        Statistics, fresh isolation plumbing) for every key of the batch."""
        out = []
        snap = self.engine.snapshot(_stale_snap_ctx(ctx, ts))
        bypass = kw.get("bypass_locks", frozenset())
        getter = PointGetter(snap, ts, **kw)
        for key in keys:
            k = Key.from_raw(key)
            self.cm.read_key_check(k, ts, bypass)
            v = getter.get(k)
            if v is not None:
                out.append((key, v))
        self._observe_batch("batch_get", len(keys))
        return out

    def scan(
        self,
        start: bytes,
        end: bytes | None,
        limit: int | None,
        ts: int,
        ctx: dict | None = None,
        reverse: bool = False,
        key_only: bool = False,
        isolation: IsolationLevel = IsolationLevel.SI,
        bypass_locks: frozenset[int] = frozenset(),
    ) -> list[tuple[bytes, bytes]]:
        ks = Key.from_raw(start) if start else None
        ke = Key.from_raw(end) if end is not None else None
        self.cm.read_range_check(ks, ke, ts, bypass_locks)
        snap = self.engine.snapshot(_stale_snap_ctx(ctx, ts))
        cls = BackwardScanner if reverse else ForwardScanner
        scanner = cls(snap, ts, ks, ke, isolation, bypass_locks, key_only)
        out = []
        for kv in scanner:
            out.append(kv)
            if limit is not None and len(out) >= limit:
                break
        return out

    def scan_lock(self, start: bytes | None, end: bytes | None, max_ts: int, limit: int | None = None):
        from .mvcc import MvccReader

        snap = self.engine.snapshot(None)
        reader = MvccReader(snap)
        return reader.scan_locks(
            Key.from_raw(start) if start else None,
            Key.from_raw(end) if end else None,
            lambda l: l.ts <= max_ts,
            limit,
        )

    # -- txn commands -------------------------------------------------------

    def sched_txn_command(self, cmd: Command, ctx: dict | None = None):
        return self.scheduler.run_command(cmd, ctx)

    # -- raw KV -------------------------------------------------------------

    def raw_get(self, key: bytes, ctx: dict | None = None, now: float | None = None) -> bytes | None:
        stored = self.engine.snapshot(ctx).get_cf(CF_DEFAULT, _raw_key(key))
        if stored is None:
            return None
        dec = _decode_raw_value(stored, now if now is not None else time.time())
        return None if dec is None else dec[0]

    def raw_get_key_ttl(self, key: bytes, ctx: dict | None = None, now: float | None = None) -> int | None:
        stored = self.engine.snapshot(ctx).get_cf(CF_DEFAULT, _raw_key(key))
        if stored is None:
            return None
        now = now if now is not None else time.time()
        dec = _decode_raw_value(stored, now)
        if dec is None:
            return None
        _, expire = dec
        return 0 if expire == _NO_TTL else max(0, expire - int(now))

    def raw_batch_get(self, keys: list[bytes], ctx: dict | None = None) -> list[tuple[bytes, bytes]]:
        snap = self.engine.snapshot(ctx)
        now = time.time()
        out = []
        for key in keys:
            stored = snap.get_cf(CF_DEFAULT, _raw_key(key))
            if stored is not None:
                dec = _decode_raw_value(stored, now)
                if dec is not None:
                    out.append((key, dec[0]))
        self._observe_batch("raw_batch_get", len(keys))
        return out

    def raw_put(self, key: bytes, value: bytes, ctx: dict | None = None, ttl: int = 0) -> None:
        wb = WriteBatch()
        wb.put_cf(CF_DEFAULT, _raw_key(key), _encode_raw_value(value, ttl, time.time()))
        self.engine.write(ctx, wb)

    def raw_batch_put(self, pairs: list[tuple[bytes, bytes]], ctx: dict | None = None, ttl: int = 0) -> None:
        wb = WriteBatch()
        now = time.time()
        for k, v in pairs:
            wb.put_cf(CF_DEFAULT, _raw_key(k), _encode_raw_value(v, ttl, now))
        self.engine.write(ctx, wb)
        self._observe_batch("raw_batch_put", len(pairs))

    def raw_delete(self, key: bytes, ctx: dict | None = None) -> None:
        wb = WriteBatch()
        wb.delete_cf(CF_DEFAULT, _raw_key(key))
        self.engine.write(ctx, wb)

    def raw_batch_delete(self, keys: list[bytes], ctx: dict | None = None) -> None:
        """ONE write batch for the whole key set — a single replicated write
        (and a single engine commit) instead of one per key."""
        wb = WriteBatch()
        for k in keys:
            wb.delete_cf(CF_DEFAULT, _raw_key(k))
        self.engine.write(ctx, wb)
        self._observe_batch("raw_batch_delete", len(keys))

    def raw_delete_range(self, start: bytes, end: bytes, ctx: dict | None = None) -> None:
        wb = WriteBatch()
        wb.delete_range_cf(CF_DEFAULT, _raw_key(start), _raw_key(end))
        self.engine.write(ctx, wb)

    def raw_scan(
        self,
        start: bytes,
        end: bytes | None,
        limit: int | None = None,
        ctx: dict | None = None,
        reverse: bool = False,
        key_only: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        snap = self.engine.snapshot(ctx)
        now = time.time()
        end_enc = _raw_key(end) if end is not None else RAW_PREFIX + b"\xff" * 64
        out = []
        for k, stored in snap.scan_cf(CF_DEFAULT, _raw_key(start), end_enc, None, reverse):
            dec = _decode_raw_value(stored, now)
            if dec is None:
                continue
            out.append((k[len(RAW_PREFIX):], b"" if key_only else dec[0]))
            if limit is not None and len(out) >= limit:
                break
        return out

    def raw_delete_if_expired(self, keys: list[bytes], ctx: dict | None = None,
                              now: float | None = None) -> int:
        """TTL reclamation primitive (ttl_checker.rs): delete each key ONLY
        if its current value is still expired, under the raw latches — a
        concurrent raw_put serializes against this, so a fresh live value
        can never be destroyed by a sweep that saw the old expired one."""
        now = now if now is not None else time.time()
        cid = self._raw_latches.gen_cid()
        slots = self._raw_latches.acquire_blocking(cid, keys)
        try:
            snap = self.engine.snapshot(ctx)
            wb = WriteBatch()
            n = 0
            for k in keys:
                stored = snap.get_cf(CF_DEFAULT, _raw_key(k))
                if stored is None or len(stored) < 8:
                    continue
                expire = codec.decode_u64(stored, len(stored) - 8)
                if expire != _NO_TTL and expire <= int(now):
                    wb.delete_cf(CF_DEFAULT, _raw_key(k))
                    n += 1
            if n:
                self.engine.write(ctx, wb)
            return n
        finally:
            self._raw_latches.release(cid, slots)

    def raw_compare_and_swap(
        self,
        key: bytes,
        previous: bytes | None,
        value: bytes,
        ctx: dict | None = None,
        ttl: int = 0,
    ) -> tuple[bool, bytes | None]:
        """Atomic CAS via latches (commands/compare_and_swap.rs)."""
        cid = self._raw_latches.gen_cid()
        slots = self._raw_latches.acquire_blocking(cid, [key])
        try:
            cur = self.raw_get(key, ctx)
            if cur != previous:
                return False, cur
            self.raw_put(key, value, ctx, ttl)
            return True, cur
        finally:
            self._raw_latches.release(cid, slots)
