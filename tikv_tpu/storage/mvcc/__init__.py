from .reader import (  # noqa: F401
    BackwardScanner,
    ForwardScanner,
    IsolationLevel,
    KeyIsLockedError,
    MvccReader,
    PointGetter,
    Statistics,
    WriteConflictError,
)
