"""MVCC read path: point gets and range scanners over a snapshot.

Re-expression of the reference's ``src/storage/mvcc/reader/{reader,
point_getter.rs:136, scanner/forward.rs:114, scanner/backward.rs:28}``.

Semantics (Percolator/SI):

* A read at ``ts`` must first consult CF_LOCK — a PUT/DELETE lock from a txn
  with ``lock.ts <= ts`` blocks the read (the writing txn may commit below our
  read ts) unless bypassed or pushed via ``min_commit_ts``.
* The visible version is the newest CF_WRITE entry with ``commit_ts <= ts``,
  skipping LOCK/ROLLBACK records; PUT yields a value (inline short value or
  CF_DEFAULT at ``start_ts``), DELETE yields nothing.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ...util import codec
from ..engine import CF_DEFAULT, CF_LOCK, CF_WRITE, Cursor, Snapshot
from ..txn_types import MAX_TS, Key, Lock, Write, WriteType, append_ts, split_ts


class IsolationLevel(enum.Enum):
    SI = "si"
    RC = "rc"


class KeyIsLockedError(Exception):
    def __init__(self, key: bytes, lock: Lock):
        self.key = key
        self.lock = lock
        super().__init__(f"key {key!r} is locked by txn {lock.ts} (primary {lock.primary!r})")


class WriteConflictError(Exception):
    def __init__(self, key: bytes, start_ts: int, conflict_start_ts: int, conflict_commit_ts: int):
        self.key = key
        self.start_ts = start_ts
        self.conflict_start_ts = conflict_start_ts
        self.conflict_commit_ts = conflict_commit_ts
        super().__init__(
            f"write conflict on {key!r}: txn {start_ts} vs committed "
            f"[{conflict_start_ts}, {conflict_commit_ts}]"
        )


@dataclass
class CfStatistics:
    get: int = 0
    next: int = 0
    prev: int = 0
    seek: int = 0
    seek_for_prev: int = 0
    processed_keys: int = 0

    def add(self, other: "CfStatistics") -> None:
        self.get += other.get
        self.next += other.next
        self.prev += other.prev
        self.seek += other.seek
        self.seek_for_prev += other.seek_for_prev
        self.processed_keys += other.processed_keys


@dataclass
class Statistics:
    """Per-CF cursor operation counts (tikv_kv/src/stats.rs)."""

    lock: CfStatistics = field(default_factory=CfStatistics)
    write: CfStatistics = field(default_factory=CfStatistics)
    data: CfStatistics = field(default_factory=CfStatistics)

    def add(self, other: "Statistics") -> None:
        self.lock.add(other.lock)
        self.write.add(other.write)
        self.data.add(other.data)

    def total_ops(self) -> int:
        return sum(
            s.get + s.next + s.prev + s.seek + s.seek_for_prev
            for s in (self.lock, self.write, self.data)
        )


# the largest possible ts suffix: appending desc(0) sorts after every real version
_LAST_VERSION_SUFFIX = codec.encode_u64_desc(0)


def _check_lock(
    lock_bytes: bytes,
    key_raw: bytes,
    ts: int,
    bypass_locks: frozenset[int],
) -> int:
    """Raise KeyIsLockedError if the lock blocks a read at ``ts``.

    Returns the ts to actually read at (committing-lock reads see through at
    the same ts; mirrors Lock::check_ts_conflict lock.rs:192).
    """
    lock = Lock.from_bytes(lock_bytes)
    if not lock.is_visible_to(ts, bypass_locks):
        raise KeyIsLockedError(key_raw, lock)
    return ts


class MvccReader:
    """Low-level MVCC access over a snapshot (reader.rs:90)."""

    def __init__(self, snapshot: Snapshot, statistics: Statistics | None = None):
        self.snap = snapshot
        self.stats = statistics or Statistics()

    # -- locks ------------------------------------------------------------

    def load_lock(self, key: Key) -> Lock | None:
        self.stats.lock.get += 1
        raw = self.snap.get_cf(CF_LOCK, key.encoded)
        return Lock.from_bytes(raw) if raw is not None else None

    def scan_locks(
        self,
        start: Key | None,
        end: Key | None,
        predicate=None,
        limit: int | None = None,
    ) -> list[tuple[Key, Lock]]:
        out: list[tuple[Key, Lock]] = []
        start_enc = start.encoded if start else b""
        end_enc = end.encoded if end else None
        for k, v in self.snap.scan_cf(CF_LOCK, start_enc, end_enc):
            self.stats.lock.next += 1
            lock = Lock.from_bytes(v)
            if predicate is None or predicate(lock):
                out.append((Key.from_encoded(k), lock))
                if limit is not None and len(out) >= limit:
                    break
        return out

    # -- write records ----------------------------------------------------

    def seek_write(self, key: Key, ts: int) -> tuple[int, Write] | None:
        """Newest write with commit_ts <= ts for exactly this key."""
        cur = self.snap.cursor_cf(CF_WRITE)
        self.stats.write.seek += 1
        if not cur.seek(append_ts(key.encoded, ts)):
            return None
        user_key, commit_ts = split_ts(cur.key())
        if user_key != key.encoded:
            return None
        return commit_ts, Write.from_bytes(cur.value())

    def get_txn_commit_record(self, key: Key, start_ts: int) -> list[tuple[int, Write]]:
        """All writes of txn ``start_ts`` on ``key`` (commit/rollback search)."""
        out = []
        cur = self.snap.cursor_cf(CF_WRITE)
        self.stats.write.seek += 1
        ok = cur.seek(append_ts(key.encoded, MAX_TS))
        while ok:
            user_key, commit_ts = split_ts(cur.key())
            if user_key != key.encoded:
                break
            w = Write.from_bytes(cur.value())
            if w.start_ts == start_ts:
                out.append((commit_ts, w))
            if commit_ts < start_ts and w.start_ts < start_ts:
                # writes are commit_ts-descending; nothing older can belong to us
                break
            self.stats.write.next += 1
            ok = cur.next()
        return out

    # -- values -----------------------------------------------------------

    def load_data(self, key: Key, write: Write) -> bytes:
        if write.short_value is not None:
            return write.short_value
        self.stats.data.get += 1
        v = self.snap.get_cf(CF_DEFAULT, append_ts(key.encoded, write.start_ts))
        if v is None:
            raise ValueError(f"default value missing for {key!r} @ {write.start_ts}")
        return v

    def get(
        self,
        key: Key,
        ts: int,
        isolation: IsolationLevel = IsolationLevel.SI,
        bypass_locks: frozenset[int] = frozenset(),
    ) -> bytes | None:
        return PointGetter(self.snap, ts, isolation, bypass_locks, self.stats).get(key)


class PointGetter:
    """Single-key visible-version lookup (point_getter.rs:136)."""

    def __init__(
        self,
        snapshot: Snapshot,
        ts: int,
        isolation: IsolationLevel = IsolationLevel.SI,
        bypass_locks: frozenset[int] = frozenset(),
        statistics: Statistics | None = None,
    ):
        self.snap = snapshot
        self.ts = ts
        self.isolation = isolation
        self.bypass_locks = bypass_locks
        self.stats = statistics or Statistics()

    def get(self, key: Key) -> bytes | None:
        if self.isolation == IsolationLevel.SI:
            self.stats.lock.get += 1
            lock_bytes = self.snap.get_cf(CF_LOCK, key.encoded)
            if lock_bytes is not None:
                _check_lock(lock_bytes, key.to_raw(), self.ts, self.bypass_locks)

        cur = self.snap.cursor_cf(CF_WRITE)
        self.stats.write.seek += 1
        ok = cur.seek(append_ts(key.encoded, self.ts))
        while ok:
            user_key, commit_ts = split_ts(cur.key())
            if user_key != key.encoded:
                return None
            write = Write.from_bytes(cur.value())
            if write.write_type == WriteType.PUT:
                self.stats.write.processed_keys += 1
                if write.short_value is not None:
                    return write.short_value
                self.stats.data.get += 1
                v = self.snap.get_cf(CF_DEFAULT, append_ts(key.encoded, write.start_ts))
                if v is None:
                    raise ValueError(f"default value missing for {key!r} @ {write.start_ts}")
                return v
            if write.write_type == WriteType.DELETE:
                return None
            # LOCK / ROLLBACK: look at the next (older) version
            self.stats.write.next += 1
            ok = cur.next()
        return None


class _ScannerBase:
    def __init__(
        self,
        snapshot: Snapshot,
        ts: int,
        start: Key | None,
        end: Key | None,
        isolation: IsolationLevel = IsolationLevel.SI,
        bypass_locks: frozenset[int] = frozenset(),
        key_only: bool = False,
        statistics: Statistics | None = None,
    ):
        self.snap = snapshot
        self.ts = ts
        self.start = start.encoded if start else b""
        self.end = end.encoded if end else None
        self.isolation = isolation
        self.bypass_locks = bypass_locks
        self.key_only = key_only
        self.stats = statistics or Statistics()

    def _check_range_locks(self) -> None:
        """Every lock in the scanned range must permit a read at ``ts`` —
        including locks on keys with no CF_WRITE entries yet (a prewritten
        brand-new key MUST block the scan, same as PointGetter; the reference
        walks a parallel lock cursor in forward.rs for exactly this)."""
        if self.isolation != IsolationLevel.SI:
            return
        for k, v in self.snap.scan_cf(CF_LOCK, self.start, self.end):
            self.stats.lock.next += 1
            _check_lock(v, Key.from_encoded(k).to_raw(), self.ts, self.bypass_locks)

    def _resolve_version(self, cur: Cursor, user_key: bytes) -> bytes | None:
        """From a cursor positioned at the newest candidate version of
        ``user_key`` with commit_ts <= ts, find the visible value."""
        ok = True
        while ok:
            k, _ = split_ts(cur.key())
            if k != user_key:
                return None
            write = Write.from_bytes(cur.value())
            if write.write_type == WriteType.PUT:
                self.stats.write.processed_keys += 1
                if self.key_only:
                    return b""
                if write.short_value is not None:
                    return write.short_value
                self.stats.data.get += 1
                v = self.snap.get_cf(CF_DEFAULT, append_ts(user_key, write.start_ts))
                if v is None:
                    raise ValueError(f"default value missing for {user_key!r}")
                return v
            if write.write_type == WriteType.DELETE:
                return None
            self.stats.write.next += 1
            ok = cur.next()
        return None


class ForwardScanner(_ScannerBase):
    """Ascending scan emitting (raw_key, value) of visible versions
    (scanner/forward.rs:114, latest-KV policy)."""

    def __iter__(self):
        self._check_range_locks()
        cur = self.snap.cursor_cf(CF_WRITE, upper=self.end)
        self.stats.write.seek += 1
        ok = cur.seek(self.start)
        while ok:
            user_key, commit_ts = split_ts(cur.key())
            if self.end is not None and user_key >= self.end:
                return
            if commit_ts > self.ts:
                # newer than the read point: hop to (user_key, ts)
                self.stats.write.seek += 1
                ok = cur.seek(append_ts(user_key, self.ts))
                if ok:
                    k2, _ = split_ts(cur.key())
                    if k2 == user_key:
                        value = self._resolve_version(cur, user_key)
                        if value is not None:
                            yield Key.from_encoded(user_key).to_raw(), value
                ok = self._skip_to_next_key(cur, user_key)
                continue
            value = self._resolve_version(cur, user_key)
            if value is not None:
                yield Key.from_encoded(user_key).to_raw(), value
            ok = self._skip_to_next_key(cur, user_key)

    def _skip_to_next_key(self, cur: Cursor, user_key: bytes) -> bool:
        self.stats.write.seek += 1
        ok = cur.seek(user_key + _LAST_VERSION_SUFFIX)
        while ok:
            k, _ = split_ts(cur.key())
            if k != user_key:
                return True
            self.stats.write.next += 1
            ok = cur.next()
        return False


class BackwardScanner(_ScannerBase):
    """Descending scan in (start, end] reversed order (scanner/backward.rs:28)."""

    def __iter__(self):
        self._check_range_locks()
        cur = self.snap.cursor_cf(CF_WRITE)
        # position at the last entry below `end`
        if self.end is not None:
            self.stats.write.seek_for_prev += 1
            ok = cur.seek_for_prev(self.end)
            if ok and cur.key() >= self.end:
                ok = cur.prev()
        else:
            self.stats.write.seek_for_prev += 1
            ok = cur.seek_to_last()
        while ok:
            user_key, _ = split_ts(cur.key())
            if user_key < self.start:
                return
            # move to the newest version <= ts of this key
            self.stats.write.seek += 1
            if cur.seek(append_ts(user_key, self.ts)):
                k2, _ = split_ts(cur.key())
                if k2 == user_key:
                    value = self._resolve_version(cur, user_key)
                    if value is not None:
                        yield Key.from_encoded(user_key).to_raw(), value
            # hop to just before the first version of this key
            self.stats.write.seek_for_prev += 1
            ok = cur.seek_for_prev(user_key)
            if ok and split_ts(cur.key())[0] >= user_key:
                # seek_for_prev landed on a version of user_key (its suffix
                # sorts above the bare key) — walk below it
                while ok and split_ts(cur.key())[0] >= user_key:
                    self.stats.write.prev += 1
                    ok = cur.prev()
