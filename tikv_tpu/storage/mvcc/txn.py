"""MVCC write transaction + Percolator actions.

Re-expression of ``src/storage/mvcc/txn.rs:38`` (``MvccTxn``: a buffer of CF
mutations produced by one command) and the reusable actions in
``src/storage/txn/actions/{prewrite,commit,acquire_pessimistic_lock,
check_txn_status,cleanup,gc}.rs``.

Percolator rules enforced here:

* prewrite: write-conflict check (any commit > start_ts), constraint checks
  (Insert/CheckNotExists), lock the key for start_ts with the primary
  recorded; pessimistic prewrite validates the existing pessimistic lock
* commit: the lock at start_ts becomes a Write record at commit_ts
* rollback: remove the lock, write a Rollback marker (protected if needed)
* check_txn_status: TTL expiry / min_commit_ts pushing for the primary
* resolve: commit or roll back secondaries according to the primary's fate
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..engine import CF_DEFAULT, CF_LOCK, CF_WRITE, Snapshot, WriteBatch
from ..txn_types import (
    Key,
    Lock,
    LockType,
    MAX_TS,
    Mutation,
    SHORT_VALUE_MAX_LEN,
    Write,
    WriteType,
)
from .reader import KeyIsLockedError, MvccReader, WriteConflictError


class TxnError(Exception):
    pass


class AlreadyExistsError(TxnError):
    def __init__(self, key: bytes):
        self.key = key
        super().__init__(f"key {key!r} already exists")


class TxnLockNotFoundError(TxnError):
    def __init__(self, key: Key, start_ts: int):
        self.key = key
        self.start_ts = start_ts
        super().__init__(f"lock not found for {key!r} at {start_ts}")


class CommitTsExpiredError(TxnError):
    pass


class PessimisticLockNotFoundError(TxnError):
    pass


class MvccTxn:
    """A buffer of CF mutations for one command at one start_ts (txn.rs:38)."""

    def __init__(self, start_ts: int):
        self.start_ts = start_ts
        self.wb = WriteBatch()
        self.locks_put: list[Key] = []
        self.locks_deleted: list[Key] = []

    def put_lock(self, key: Key, lock: Lock) -> None:
        self.wb.put_cf(CF_LOCK, key.encoded, lock.to_bytes())
        self.locks_put.append(key)

    def unlock_key(self, key: Key) -> None:
        self.wb.delete_cf(CF_LOCK, key.encoded)
        self.locks_deleted.append(key)

    def put_value(self, key: Key, ts: int, value: bytes) -> None:
        self.wb.put_cf(CF_DEFAULT, key.append_ts(ts).encoded, value)

    def delete_value(self, key: Key, ts: int) -> None:
        self.wb.delete_cf(CF_DEFAULT, key.append_ts(ts).encoded)

    def put_write(self, key: Key, commit_ts: int, write: Write) -> None:
        self.wb.put_cf(CF_WRITE, key.append_ts(commit_ts).encoded, write.to_bytes())

    def delete_write(self, key: Key, commit_ts: int) -> None:
        self.wb.delete_cf(CF_WRITE, key.append_ts(commit_ts).encoded)

    def is_empty(self) -> bool:
        return self.wb.is_empty()


# ---------------------------------------------------------------------------
# prewrite (actions/prewrite.rs:21)
# ---------------------------------------------------------------------------

@dataclass
class PrewriteContext:
    primary: bytes
    start_ts: int
    lock_ttl: int = 3000
    txn_size: int = 0
    min_commit_ts: int = 0
    use_async_commit: bool = False
    secondaries: list[bytes] = field(default_factory=list)
    is_pessimistic: bool = False


def prewrite_key(
    txn: MvccTxn,
    reader: MvccReader,
    mutation: Mutation,
    ctx: PrewriteContext,
    is_pessimistic_lock: bool = False,
) -> int:
    """Prewrite one mutation. Returns min_commit_ts for async commit (0 else).

    ``is_pessimistic_lock``: this key was locked by AcquirePessimisticLock
    earlier in the same txn (pessimistic prewrite path).
    """
    key = mutation.key
    lock = reader.load_lock(key)
    if lock is not None:
        if lock.ts != ctx.start_ts:
            if ctx.is_pessimistic and is_pessimistic_lock:
                raise PessimisticLockNotFoundError(f"pessimistic lock lost on {key!r}")
            raise KeyIsLockedError(key.to_raw(), lock)
        if lock.lock_type != LockType.PESSIMISTIC:
            # duplicate prewrite: idempotent, keep existing
            return lock.min_commit_ts
        # pessimistic lock exists: will be upgraded below
    elif ctx.is_pessimistic and is_pessimistic_lock:
        raise PessimisticLockNotFoundError(f"pessimistic lock missing on {key!r}")

    skip_conflict_check = ctx.is_pessimistic and is_pessimistic_lock
    if not skip_conflict_check:
        rec = reader.seek_write(key, MAX_TS)
        if rec is not None:
            commit_ts, write = rec
            if commit_ts >= ctx.start_ts:
                # a commit above us: write conflict (optimistic) — except a
                # rollback of our own ts, which means we were rolled back
                raise WriteConflictError(key.to_raw(), ctx.start_ts, write.start_ts, commit_ts)
        if mutation.should_not_exists():
            _check_not_exists(reader, key, ctx.start_ts)
    else:
        if mutation.should_not_exists():
            _check_not_exists(reader, key, ctx.start_ts)

    # our own rollback marker ⇒ the txn has been rolled back already
    for commit_ts, write in reader.get_txn_commit_record(key, ctx.start_ts):
        if write.write_type == WriteType.ROLLBACK:
            raise WriteConflictError(key.to_raw(), ctx.start_ts, ctx.start_ts, commit_ts)

    if mutation.mutation_type.value == "check_not_exists":
        return 0

    lock = Lock(
        mutation.lock_type(),
        ctx.primary,
        ctx.start_ts,
        ttl=ctx.lock_ttl,
        txn_size=ctx.txn_size,
        min_commit_ts=ctx.min_commit_ts,
        use_async_commit=ctx.use_async_commit,
        secondaries=list(ctx.secondaries) if key.to_raw() == ctx.primary else [],
    )
    value = mutation.value
    if value is not None:
        if len(value) <= SHORT_VALUE_MAX_LEN:
            lock.short_value = value
        else:
            txn.put_value(key, ctx.start_ts, value)
    min_commit_ts = 0
    if ctx.use_async_commit:
        min_commit_ts = max(ctx.min_commit_ts, ctx.start_ts + 1)
        lock.min_commit_ts = min_commit_ts
    txn.put_lock(key, lock)
    return min_commit_ts


def _check_not_exists(reader: MvccReader, key: Key, start_ts: int) -> None:
    rec = reader.seek_write(key, MAX_TS)
    while rec is not None:
        commit_ts, write = rec
        if write.write_type == WriteType.PUT:
            raise AlreadyExistsError(key.to_raw())
        if write.write_type == WriteType.DELETE:
            return
        rec = reader.seek_write(key, commit_ts - 1)


# ---------------------------------------------------------------------------
# acquire pessimistic lock (actions/acquire_pessimistic_lock.rs)
# ---------------------------------------------------------------------------

def acquire_pessimistic_lock(
    txn: MvccTxn,
    reader: MvccReader,
    key: Key,
    primary: bytes,
    start_ts: int,
    for_update_ts: int,
    ttl: int = 3000,
    should_not_exist: bool = False,
) -> bytes | None:
    """Lock a key for a pessimistic txn; returns the current value if any."""
    lock = reader.load_lock(key)
    if lock is not None:
        if lock.ts != start_ts:
            raise KeyIsLockedError(key.to_raw(), lock)
        # already locked by us: refresh for_update_ts if newer
        if for_update_ts > lock.for_update_ts:
            lock.for_update_ts = for_update_ts
            txn.put_lock(key, lock)
        return None
    rec = reader.seek_write(key, MAX_TS)
    value = None
    if rec is not None:
        commit_ts, write = rec
        if commit_ts > for_update_ts:
            raise WriteConflictError(key.to_raw(), start_ts, write.start_ts, commit_ts)
        # rollback of our own start_ts means we were rolled back
        for cts, w in reader.get_txn_commit_record(key, start_ts):
            if w.write_type == WriteType.ROLLBACK:
                raise WriteConflictError(key.to_raw(), start_ts, start_ts, cts)
        # LOCK/ROLLBACK markers hide the live version — walk to the newest
        # PUT/DELETE (same loop as _check_not_exists)
        while rec is not None and rec[1].write_type not in (WriteType.PUT, WriteType.DELETE):
            rec = reader.seek_write(key, rec[0] - 1)
        if rec is not None and rec[1].write_type == WriteType.PUT:
            value = reader.load_data(key, rec[1])
            if should_not_exist:
                raise AlreadyExistsError(key.to_raw())
    lock = Lock(LockType.PESSIMISTIC, primary, start_ts, ttl=ttl, for_update_ts=for_update_ts)
    txn.put_lock(key, lock)
    return value


# ---------------------------------------------------------------------------
# commit (actions/commit.rs)
# ---------------------------------------------------------------------------

def commit_key(txn: MvccTxn, reader: MvccReader, key: Key, start_ts: int, commit_ts: int) -> Lock | None:
    lock = reader.load_lock(key)
    if lock is None or lock.ts != start_ts:
        # committed already? look for the write record
        for cts, w in reader.get_txn_commit_record(key, start_ts):
            if w.write_type != WriteType.ROLLBACK:
                return None  # idempotent re-commit
        raise TxnLockNotFoundError(key, start_ts)
    if lock.lock_type == LockType.PESSIMISTIC:
        # commit of a pessimistic lock without prewrite: roll it back to a
        # LOCK-type record (commit.rs handles this as lock-type fallthrough)
        lock.lock_type = LockType.LOCK
    if commit_ts < lock.min_commit_ts:
        raise CommitTsExpiredError(
            f"commit_ts {commit_ts} < min_commit_ts {lock.min_commit_ts} for {key!r}"
        )
    wt = {
        LockType.PUT: WriteType.PUT,
        LockType.DELETE: WriteType.DELETE,
        LockType.LOCK: WriteType.LOCK,
    }[lock.lock_type]
    write = Write(wt, start_ts, short_value=lock.short_value)
    txn.put_write(key, commit_ts, write)
    txn.unlock_key(key)
    return lock


# ---------------------------------------------------------------------------
# cleanup / rollback (actions/cleanup.rs, check_txn_status.rs)
# ---------------------------------------------------------------------------

def rollback_key(
    txn: MvccTxn, reader: MvccReader, key: Key, start_ts: int, protect: bool = False
) -> None:
    lock = reader.load_lock(key)
    if lock is not None and lock.ts == start_ts:
        if lock.short_value is None and lock.lock_type == LockType.PUT:
            txn.delete_value(key, start_ts)
        txn.unlock_key(key)
        txn.put_write(key, start_ts, Write.new_rollback(start_ts, protect))
        return
    # no lock: check commit record
    for commit_ts, w in reader.get_txn_commit_record(key, start_ts):
        if w.write_type == WriteType.ROLLBACK:
            return  # already rolled back
        raise TxnError(f"txn {start_ts} already committed at {commit_ts} on {key!r}")
    # neither lock nor record: leave a protected rollback tombstone
    txn.put_write(key, start_ts, Write.new_rollback(start_ts, protect))


class TxnStatusKind(enum.Enum):
    COMMITTED = "committed"
    ROLLED_BACK = "rolled_back"
    LOCKED = "locked"
    TTL_EXPIRED = "ttl_expired"
    MIN_COMMIT_PUSHED = "min_commit_pushed"
    NOT_FOUND = "not_found"


@dataclass
class TxnStatus:
    kind: TxnStatusKind
    commit_ts: int = 0
    lock_ttl: int = 0
    min_commit_ts: int = 0
    # Set on LOCKED results for async-commit locks so the client knows to
    # resolve via check_secondary_locks / force_sync_commit instead of
    # retrying check_txn_status forever (the reference returns the full
    # LockInfo in TxnStatus::uncommitted for this purpose).
    use_async_commit: bool = False


def check_txn_status(
    txn: MvccTxn,
    reader: MvccReader,
    primary_key: Key,
    lock_ts: int,
    caller_start_ts: int,
    current_ts: int,
    rollback_if_not_exist: bool = False,
    now_ms: int | None = None,
    force_sync_commit: bool = False,
) -> TxnStatus:
    """Primary-key liveness check (actions/check_txn_status.rs).

    Async-commit locks are never rolled back or pushed here, regardless of
    TTL: the transaction may already be decided committed through its
    secondaries, so resolution is CheckSecondaryLocks/ResolveLock's job
    (actions/check_txn_status.rs:26 returns uncommitted for
    use_async_commit locks unless the client set force_sync_commit).
    """
    from ..txn_types import ts_physical

    lock = reader.load_lock(primary_key)
    if lock is not None and lock.ts == lock_ts:
        if lock.use_async_commit and not force_sync_commit:
            return TxnStatus(
                TxnStatusKind.LOCKED, lock_ttl=lock.ttl,
                min_commit_ts=lock.min_commit_ts, use_async_commit=True,
            )
        lock_elapsed = ts_physical(current_ts) - ts_physical(lock_ts)
        if lock_elapsed >= lock.ttl:
            rollback_key(txn, reader, primary_key, lock_ts, protect=True)
            return TxnStatus(TxnStatusKind.TTL_EXPIRED)
        # push min_commit_ts so readers above caller_start_ts can proceed
        if caller_start_ts >= lock.min_commit_ts:
            lock.min_commit_ts = caller_start_ts + 1
            txn.put_lock(primary_key, lock)
            return TxnStatus(
                TxnStatusKind.MIN_COMMIT_PUSHED, lock_ttl=lock.ttl, min_commit_ts=lock.min_commit_ts
            )
        return TxnStatus(TxnStatusKind.LOCKED, lock_ttl=lock.ttl, min_commit_ts=lock.min_commit_ts)
    for commit_ts, w in reader.get_txn_commit_record(primary_key, lock_ts):
        if w.write_type == WriteType.ROLLBACK:
            return TxnStatus(TxnStatusKind.ROLLED_BACK)
        return TxnStatus(TxnStatusKind.COMMITTED, commit_ts=commit_ts)
    if rollback_if_not_exist:
        rollback_key(txn, reader, primary_key, lock_ts, protect=True)
        return TxnStatus(TxnStatusKind.ROLLED_BACK)
    return TxnStatus(TxnStatusKind.NOT_FOUND)
