"""In-memory per-key lock table + max_ts tracking.

Re-expression of ``components/concurrency_manager`` (``src/lib.rs:33``):
async-commit prewrites hold *memory* locks on their keys so point/range reads
can detect them before the persisted lock is visible, and every read advances
``max_ts`` so async-commit transactions can compute a safe min_commit_ts.
"""

from __future__ import annotations

from ..analysis.sanitizer import make_rlock
from .mvcc.reader import KeyIsLockedError
from .txn_types import Key, Lock


class KeyHandleGuard:
    def __init__(self, cm: "ConcurrencyManager", key: Key):
        self._cm = cm
        self.key = key
        self._lock: Lock | None = None

    def with_lock(self, lock: Lock | None) -> None:
        with self._cm._mu:
            if lock is None:
                self._cm._table.pop(self.key.encoded, None)
            else:
                self._cm._table[self.key.encoded] = lock
            self._lock = lock

    def release(self) -> None:
        self.with_lock(None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class ConcurrencyManager:
    def __init__(self, latest_ts: int = 0):
        self._mu = make_rlock("txn.concurrency_manager")
        self._max_ts = latest_ts
        self._table: dict[bytes, Lock] = {}

    def max_ts(self) -> int:
        with self._mu:
            return self._max_ts

    def update_max_ts(self, ts: int) -> None:
        with self._mu:
            if ts > self._max_ts:
                self._max_ts = ts

    def lock_key(self, key: Key) -> KeyHandleGuard:
        return KeyHandleGuard(self, key)

    def read_key_check(self, key: Key, ts: int, bypass: frozenset[int] = frozenset()) -> None:
        self.update_max_ts(ts)
        with self._mu:
            lock = self._table.get(key.encoded)
        if lock is not None and not lock.is_visible_to(ts, bypass):
            raise KeyIsLockedError(key.to_raw(), lock)

    def read_range_check(
        self, start: Key | None, end: Key | None, ts: int, bypass: frozenset[int] = frozenset()
    ) -> None:
        self.update_max_ts(ts)
        with self._mu:
            items = list(self._table.items())
        for enc, lock in items:
            if start is not None and enc < start.encoded:
                continue
            if end is not None and enc >= end.encoded:
                continue
            if not lock.is_visible_to(ts, bypass):
                raise KeyIsLockedError(Key.from_encoded(enc).to_raw(), lock)

    def global_min_lock_ts(self) -> int | None:
        with self._mu:
            return min((l.ts for l in self._table.values()), default=None)
