"""Ordered in-memory multi-CF engine with O(log n) seeks and cheap snapshots.

Plays the role of the reference's ``tikv_kv/src/btree_engine.rs`` (the in-memory
test engine) *and* stands in for RocksDB until the native C++ engine is wired
in.  Each CF is a sorted key list + value dict; snapshots freeze the current
state and the engine clones a CF's state lazily on the first write after a
snapshot (copy-on-write at CF granularity), so read-heavy workloads never copy.

``bulk_load`` ingests a pre-sorted batch without per-key list insertion — the
coprocessor benchmarks load millions of MVCC rows through it.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterator

from .engine import ALL_CFS, Cursor, KvEngine, Snapshot, WriteBatch


class _CfState:
    """Immutable-once-frozen sorted state of one column family."""

    __slots__ = ("keys", "vals", "frozen")

    def __init__(self, keys: list[bytes] | None = None, vals: dict[bytes, bytes] | None = None):
        self.keys: list[bytes] = keys if keys is not None else []
        self.vals: dict[bytes, bytes] = vals if vals is not None else {}
        self.frozen = False

    def clone(self) -> "_CfState":
        return _CfState(list(self.keys), dict(self.vals))


class _ListCursor(Cursor):
    __slots__ = ("_keys", "_vals", "_lo", "_hi", "_pos")

    def __init__(self, state: _CfState, lower: bytes | None, upper: bytes | None):
        self._keys = state.keys
        self._vals = state.vals
        self._lo = 0 if lower is None else bisect.bisect_left(self._keys, lower)
        self._hi = len(self._keys) if upper is None else bisect.bisect_left(self._keys, upper)
        self._pos = -1

    def seek(self, key: bytes) -> bool:
        self._pos = max(bisect.bisect_left(self._keys, key), self._lo)
        return self.valid()

    def seek_for_prev(self, key: bytes) -> bool:
        self._pos = min(bisect.bisect_right(self._keys, key), self._hi) - 1
        return self.valid()

    def seek_to_first(self) -> bool:
        self._pos = self._lo
        return self.valid()

    def seek_to_last(self) -> bool:
        self._pos = self._hi - 1
        return self.valid()

    def next(self) -> bool:
        self._pos += 1
        return self.valid()

    def prev(self) -> bool:
        self._pos -= 1
        return self.valid()

    def valid(self) -> bool:
        return self._lo <= self._pos < self._hi

    def key(self) -> bytes:
        return self._keys[self._pos]

    def value(self) -> bytes:
        return self._vals[self._keys[self._pos]]


class BTreeSnapshot(Snapshot):
    __slots__ = ("_states",)

    def __init__(self, states: dict[str, _CfState]):
        self._states = states

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        return self._states[cf].vals.get(key)

    def cursor_cf(self, cf: str, lower: bytes | None = None, upper: bytes | None = None) -> Cursor:
        return _ListCursor(self._states[cf], lower, upper)


class BTreeEngine(KvEngine):
    def __init__(self, cfs: tuple[str, ...] = ALL_CFS):
        self._lock = threading.RLock()
        self._cfs: dict[str, _CfState] = {cf: _CfState() for cf in cfs}

    def _writable(self, cf: str) -> _CfState:
        state = self._cfs[cf]
        if state.frozen:
            state = state.clone()
            self._cfs[cf] = state
        return state

    def write(self, batch: WriteBatch) -> None:
        with self._lock:
            for op, cf, key, val in batch.ops:
                state = self._writable(cf)
                if op == "put":
                    if key not in state.vals:
                        bisect.insort(state.keys, key)
                    state.vals[key] = val
                elif op == "delete":
                    if key in state.vals:
                        del state.vals[key]
                        i = bisect.bisect_left(state.keys, key)
                        del state.keys[i]
                elif op == "delete_range":
                    lo = bisect.bisect_left(state.keys, key)
                    hi = bisect.bisect_left(state.keys, val)
                    for k in state.keys[lo:hi]:
                        del state.vals[k]
                    del state.keys[lo:hi]
                else:
                    raise ValueError(f"unknown op {op}")

    def bulk_load(self, cf: str, items: list[tuple[bytes, bytes]]) -> None:
        """Merge a batch of (key, value) pairs in one sort — O((n+m) log(n+m))."""
        with self._lock:
            state = self._writable(cf)
            state.vals.update(items)
            state.keys = sorted(state.vals)

    def snapshot(self) -> BTreeSnapshot:
        with self._lock:
            for state in self._cfs.values():
                state.frozen = True
            return BTreeSnapshot(dict(self._cfs))

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        with self._lock:
            return self._cfs[cf].vals.get(key)

    def snapshot_cf(self, cf: str) -> BTreeSnapshot:
        """Snapshot freezing only one CF — scans shouldn't tax writes to other CFs."""
        with self._lock:
            state = self._cfs[cf]
            state.frozen = True
            return BTreeSnapshot({cf: state})

    def scan_cf(
        self,
        cf: str,
        start: bytes,
        end: bytes | None,
        limit: int | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        # Materialize the range under the lock rather than snapshotting: a
        # snapshot freezes the CF and forces the next write to clone it (O(n)).
        with self._lock:
            state = self._cfs[cf]
            lo = bisect.bisect_left(state.keys, start)
            hi = len(state.keys) if end is None else bisect.bisect_left(state.keys, end)
            keys = state.keys[lo:hi]
            if reverse:
                keys = keys[::-1]
            if limit is not None:
                keys = keys[:limit]
            items = [(k, state.vals[k]) for k in keys]
        return iter(items)
