"""Storage-engine trait layer.

Re-expression of ``components/engine_traits`` (``engine.rs:13``,
``peekable.rs:11``, ``iterable.rs:130``, ``write_batch.rs:33,82``,
``snapshot.rs:11``, ``cf_defs.rs``): a small set of abstract interfaces that
decouple everything above (MVCC, txn, raftstore, coprocessor) from the concrete
storage medium.  Implementations in this package:

* ``btree_engine.BTreeEngine`` — ordered in-memory engine (tests + default)
* ``native`` C++ engine (ctypes) — drop-in once built

Column families mirror ``cf_defs.rs``: default / lock / write / raft.
"""

from __future__ import annotations

import abc
from typing import Iterator

CF_DEFAULT = "default"
CF_LOCK = "lock"
CF_WRITE = "write"
CF_RAFT = "raft"
ALL_CFS = (CF_DEFAULT, CF_LOCK, CF_WRITE, CF_RAFT)
DATA_CFS = (CF_DEFAULT, CF_LOCK, CF_WRITE)


class Cursor(abc.ABC):
    """A bidirectional iterator over one CF of a snapshot.

    Semantics follow ``engine_traits::Iterator`` (iterable.rs:33-127): the
    cursor is either valid (positioned on an entry) or invalid; seeks position
    it at the first entry >= key (``seek``) or last entry <= key
    (``seek_for_prev``).
    """

    @abc.abstractmethod
    def seek(self, key: bytes) -> bool: ...

    @abc.abstractmethod
    def seek_for_prev(self, key: bytes) -> bool: ...

    @abc.abstractmethod
    def seek_to_first(self) -> bool: ...

    @abc.abstractmethod
    def seek_to_last(self) -> bool: ...

    @abc.abstractmethod
    def next(self) -> bool: ...

    @abc.abstractmethod
    def prev(self) -> bool: ...

    @abc.abstractmethod
    def valid(self) -> bool: ...

    @abc.abstractmethod
    def key(self) -> bytes: ...

    @abc.abstractmethod
    def value(self) -> bytes: ...


class Snapshot(abc.ABC):
    """A consistent, immutable view of the engine (snapshot.rs:11)."""

    @abc.abstractmethod
    def get_cf(self, cf: str, key: bytes) -> bytes | None: ...

    @abc.abstractmethod
    def cursor_cf(self, cf: str, lower: bytes | None = None, upper: bytes | None = None) -> Cursor: ...

    def get(self, key: bytes) -> bytes | None:
        return self.get_cf(CF_DEFAULT, key)

    def cursor(self, lower: bytes | None = None, upper: bytes | None = None) -> Cursor:
        return self.cursor_cf(CF_DEFAULT, lower, upper)

    def scan_cf(
        self,
        cf: str,
        start: bytes,
        end: bytes | None,
        limit: int | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        """Yield (key, value) in [start, end) — convenience over cursors."""
        cur = self.cursor_cf(cf, lower=None if reverse else start, upper=end)
        n = 0
        if reverse:
            ok = cur.seek_for_prev(end) if end is not None else cur.seek_to_last()
            # end is exclusive
            if ok and end is not None and cur.key() >= end:
                ok = cur.prev()
            while ok and (limit is None or n < limit):
                if cur.key() < start:
                    break
                yield cur.key(), cur.value()
                n += 1
                ok = cur.prev()
        else:
            ok = cur.seek(start)
            while ok and (limit is None or n < limit):
                if end is not None and cur.key() >= end:
                    break
                yield cur.key(), cur.value()
                n += 1
                ok = cur.next()


class WriteBatch:
    """Ordered list of mutations applied atomically (write_batch.rs:33,82)."""

    __slots__ = ("ops",)

    def __init__(self):
        # (op, cf, key, value_or_end_key)
        self.ops: list[tuple[str, str, bytes, bytes | None]] = []

    def put(self, key: bytes, value: bytes) -> None:
        self.put_cf(CF_DEFAULT, key, value)

    def put_cf(self, cf: str, key: bytes, value: bytes) -> None:
        self.ops.append(("put", cf, key, value))

    def delete(self, key: bytes) -> None:
        self.delete_cf(CF_DEFAULT, key)

    def delete_cf(self, cf: str, key: bytes) -> None:
        self.ops.append(("delete", cf, key, None))

    def delete_range_cf(self, cf: str, start: bytes, end: bytes) -> None:
        self.ops.append(("delete_range", cf, start, end))

    def is_empty(self) -> bool:
        return not self.ops

    def count(self) -> int:
        return len(self.ops)

    def clear(self) -> None:
        self.ops.clear()

    def merge(self, other: "WriteBatch") -> None:
        self.ops.extend(other.ops)


class KvEngine(abc.ABC):
    """The full engine interface (engine.rs:13): point ops + batches + snapshots."""

    @abc.abstractmethod
    def write(self, batch: WriteBatch) -> None: ...

    @abc.abstractmethod
    def snapshot(self) -> Snapshot: ...

    @abc.abstractmethod
    def get_cf(self, cf: str, key: bytes) -> bytes | None: ...

    def get(self, key: bytes) -> bytes | None:
        return self.get_cf(CF_DEFAULT, key)

    def put_cf(self, cf: str, key: bytes, value: bytes) -> None:
        wb = WriteBatch()
        wb.put_cf(cf, key, value)
        self.write(wb)

    def delete_cf(self, cf: str, key: bytes) -> None:
        wb = WriteBatch()
        wb.delete_cf(cf, key)
        self.write(wb)

    @abc.abstractmethod
    def scan_cf(
        self,
        cf: str,
        start: bytes,
        end: bytes | None,
        limit: int | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]: ...

    def flush(self) -> None:  # durability hook; in-memory engines no-op
        pass
