"""MVCC data model: timestamps, keys, locks, write records, mutations.

Re-expression of the reference's ``components/txn_types/src/{timestamp,types,
lock,write}.rs``.  The on-disk layouts keep the reference's *structure* (flag
byte + varint fields + optional tagged extensions) so that every capability —
short-value inlining, overlapped rollback, gc fence, async commit secondaries,
rollback-ts protection — has a place, but the exact byte tags are this
framework's own.

Physical layout of the three MVCC column families (same as the reference):

* ``CF_DEFAULT``: ``encoded_user_key + desc(start_ts)`` → value
* ``CF_LOCK``:    ``encoded_user_key``                  → Lock record
* ``CF_WRITE``:   ``encoded_user_key + desc(commit_ts)`` → Write record

``desc(ts)`` is the bit-flipped big-endian u64 so newer versions sort first.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..util import codec

# ---------------------------------------------------------------------------
# TimeStamp  (txn_types/src/timestamp.rs:9 — physical<<18 | logical)
# ---------------------------------------------------------------------------

TSO_PHYSICAL_SHIFT_BITS = 18
MAX_TS = 0xFFFFFFFFFFFFFFFF


def compose_ts(physical_ms: int, logical: int) -> int:
    return (physical_ms << TSO_PHYSICAL_SHIFT_BITS) + logical


def ts_physical(ts: int) -> int:
    return ts >> TSO_PHYSICAL_SHIFT_BITS


def ts_logical(ts: int) -> int:
    return ts & ((1 << TSO_PHYSICAL_SHIFT_BITS) - 1)


def ts_next(ts: int) -> int:
    assert ts < MAX_TS
    return ts + 1


def ts_prev(ts: int) -> int:
    assert ts > 0
    return ts - 1


# ---------------------------------------------------------------------------
# Key  (txn_types/src/types.rs:42 — memcomparable-encoded user key)
# ---------------------------------------------------------------------------

class Key:
    """A memcomparable-encoded key, optionally suffixed with a desc timestamp."""

    __slots__ = ("encoded",)

    def __init__(self, encoded: bytes):
        self.encoded = encoded

    @classmethod
    def from_raw(cls, raw: bytes) -> "Key":
        return cls(codec.encode_bytes(raw))

    @classmethod
    def from_encoded(cls, encoded: bytes) -> "Key":
        return cls(encoded)

    def to_raw(self) -> bytes:
        data, consumed = codec.decode_bytes(self.encoded)
        if consumed != len(self.encoded):
            raise ValueError("key has trailing bytes (timestamp suffix?)")
        return data

    def append_ts(self, ts: int) -> "Key":
        return Key(self.encoded + codec.encode_u64_desc(ts))

    def decode_ts(self) -> int:
        if len(self.encoded) < 8:
            raise ValueError("key too short for ts")
        return codec.decode_u64_desc(self.encoded, len(self.encoded) - 8)

    def truncate_ts(self) -> "Key":
        if len(self.encoded) < 8:
            raise ValueError("key too short for ts")
        return Key(self.encoded[:-8])

    def split_on_ts(self) -> tuple["Key", int]:
        return self.truncate_ts(), self.decode_ts()

    def is_encoded_from(self, raw: bytes) -> bool:
        try:
            return self.to_raw() == raw
        except ValueError:
            return False

    def __eq__(self, other) -> bool:
        return isinstance(other, Key) and self.encoded == other.encoded

    def __lt__(self, other: "Key") -> bool:
        return self.encoded < other.encoded

    def __hash__(self) -> int:
        return hash(self.encoded)

    def __repr__(self) -> str:
        return f"Key({self.encoded.hex()})"


def append_ts(encoded_key: bytes, ts: int) -> bytes:
    return encoded_key + codec.encode_u64_desc(ts)


def split_ts(encoded_key_with_ts: bytes) -> tuple[bytes, int]:
    if len(encoded_key_with_ts) < 8:
        raise ValueError("key too short for ts suffix")
    return (
        encoded_key_with_ts[:-8],
        codec.decode_u64_desc(encoded_key_with_ts, len(encoded_key_with_ts) - 8),
    )


# ---------------------------------------------------------------------------
# Write records  (txn_types/src/write.rs:13,63,224)
# ---------------------------------------------------------------------------

SHORT_VALUE_MAX_LEN = 255
_SHORT_VALUE_PREFIX = 0x76  # b'v'
_FLAG_OVERLAPPED_ROLLBACK = 0x52  # b'R'
_GC_FENCE_PREFIX = 0x46  # b'F'


class WriteType(enum.IntEnum):
    PUT = 0x50  # b'P'
    DELETE = 0x44  # b'D'
    LOCK = 0x4C  # b'L'
    ROLLBACK = 0x52  # b'R'


@dataclass
class Write:
    """A committed version record stored in CF_WRITE under key+commit_ts."""

    write_type: WriteType
    start_ts: int
    short_value: bytes | None = None
    has_overlapped_rollback: bool = False
    # gc_fence semantics (write.rs:78-129): None = not set; 0 = deleted/
    # rewritten tail version; >0 = next version's commit ts after a rewrite.
    gc_fence: int | None = None

    def to_bytes(self) -> bytes:
        out = bytearray()
        out.append(int(self.write_type))
        out += codec.encode_var_u64(self.start_ts)
        if self.short_value is not None:
            if len(self.short_value) > SHORT_VALUE_MAX_LEN:
                raise ValueError("short value too long")
            out.append(_SHORT_VALUE_PREFIX)
            out.append(len(self.short_value))
            out += self.short_value
        if self.has_overlapped_rollback:
            out.append(_FLAG_OVERLAPPED_ROLLBACK)
        if self.gc_fence is not None:
            out.append(_GC_FENCE_PREFIX)
            out += codec.encode_u64(self.gc_fence)
        return bytes(out)

    @classmethod
    def from_bytes(cls, b: bytes) -> "Write":
        if not b:
            raise ValueError("empty write record")
        try:
            wt = WriteType(b[0])
        except ValueError as e:
            raise ValueError(str(e)) from None
        start_ts, off = codec.decode_var_u64(b, 1)
        short_value = None
        overlapped = False
        gc_fence = None
        while off < len(b):
            tag = b[off]
            off += 1
            if tag == _SHORT_VALUE_PREFIX:
                if off >= len(b):
                    raise ValueError("write record truncated in short value length")
                n = b[off]
                off += 1
                if off + n > len(b):
                    raise ValueError("write record truncated in short value")
                short_value = b[off : off + n]
                off += n
            elif tag == _FLAG_OVERLAPPED_ROLLBACK:
                overlapped = True
            elif tag == _GC_FENCE_PREFIX:
                if off + 8 > len(b):
                    raise ValueError("write record truncated in gc fence")
                gc_fence = codec.decode_u64(b, off)
                off += 8
            else:
                raise ValueError(f"unknown write tag {tag:#x}")
        return cls(wt, start_ts, short_value, overlapped, gc_fence)

    def is_protected(self) -> bool:
        """A protected rollback must not be collapsed (write.rs:186)."""
        return self.write_type == WriteType.ROLLBACK and self.short_value == b"P"

    @classmethod
    def new_rollback(cls, start_ts: int, protected: bool) -> "Write":
        return cls(WriteType.ROLLBACK, start_ts, b"P" if protected else None)


# ---------------------------------------------------------------------------
# Locks  (txn_types/src/lock.rs:13,62)
# ---------------------------------------------------------------------------

_TAG_SHORT_VALUE = 0x76  # b'v'
_TAG_FOR_UPDATE_TS = 0x66  # b'f'
_TAG_TXN_SIZE = 0x74  # b't'
_TAG_MIN_COMMIT_TS = 0x63  # b'c'
_TAG_ASYNC_COMMIT = 0x61  # b'a'
_TAG_ROLLBACK_TS = 0x72  # b'r'


class LockType(enum.IntEnum):
    PUT = 0x50  # b'P'
    DELETE = 0x44  # b'D'
    LOCK = 0x4C  # b'L'
    PESSIMISTIC = 0x53  # b'S'


@dataclass
class Lock:
    """An uncommitted lock stored in CF_LOCK under the user key."""

    lock_type: LockType
    primary: bytes
    ts: int  # start_ts of the locking txn
    ttl: int = 0
    short_value: bytes | None = None
    for_update_ts: int = 0  # >0 ⇒ pessimistic txn
    txn_size: int = 0
    min_commit_ts: int = 0
    use_async_commit: bool = False
    secondaries: list[bytes] = field(default_factory=list)
    rollback_ts: list[int] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = bytearray()
        out.append(int(self.lock_type))
        out += codec.encode_compact_bytes(self.primary)
        out += codec.encode_var_u64(self.ts)
        out += codec.encode_var_u64(self.ttl)
        if self.short_value is not None:
            if len(self.short_value) > SHORT_VALUE_MAX_LEN:
                raise ValueError("short value too long")
            out.append(_TAG_SHORT_VALUE)
            out.append(len(self.short_value))
            out += self.short_value
        if self.for_update_ts:
            out.append(_TAG_FOR_UPDATE_TS)
            out += codec.encode_u64(self.for_update_ts)
        if self.txn_size:
            out.append(_TAG_TXN_SIZE)
            out += codec.encode_u64(self.txn_size)
        if self.min_commit_ts:
            out.append(_TAG_MIN_COMMIT_TS)
            out += codec.encode_u64(self.min_commit_ts)
        if self.use_async_commit:
            out.append(_TAG_ASYNC_COMMIT)
            out += codec.encode_var_u64(len(self.secondaries))
            for s in self.secondaries:
                out += codec.encode_compact_bytes(s)
        if self.rollback_ts:
            out.append(_TAG_ROLLBACK_TS)
            out += codec.encode_var_u64(len(self.rollback_ts))
            for ts in self.rollback_ts:
                out += codec.encode_u64(ts)
        return bytes(out)

    @classmethod
    def from_bytes(cls, b: bytes) -> "Lock":
        if not b:
            raise ValueError("empty lock record")
        try:
            lt = LockType(b[0])
        except ValueError as e:
            raise ValueError(str(e)) from None
        primary, off = codec.decode_compact_bytes(b, 1)
        ts, off = codec.decode_var_u64(b, off)
        ttl, off = codec.decode_var_u64(b, off)
        lock = cls(lt, primary, ts, ttl)

        def need(n: int) -> None:
            if off + n > len(b):
                raise ValueError("lock record truncated")

        while off < len(b):
            tag = b[off]
            off += 1
            if tag == _TAG_SHORT_VALUE:
                need(1)
                n = b[off]
                off += 1
                need(n)
                lock.short_value = b[off : off + n]
                off += n
            elif tag == _TAG_FOR_UPDATE_TS:
                need(8)
                lock.for_update_ts = codec.decode_u64(b, off)
                off += 8
            elif tag == _TAG_TXN_SIZE:
                need(8)
                lock.txn_size = codec.decode_u64(b, off)
                off += 8
            elif tag == _TAG_MIN_COMMIT_TS:
                need(8)
                lock.min_commit_ts = codec.decode_u64(b, off)
                off += 8
            elif tag == _TAG_ASYNC_COMMIT:
                lock.use_async_commit = True
                n, off = codec.decode_var_u64(b, off)
                for _ in range(n):
                    s, off = codec.decode_compact_bytes(b, off)
                    lock.secondaries.append(s)
            elif tag == _TAG_ROLLBACK_TS:
                n, off = codec.decode_var_u64(b, off)
                need(8 * n)
                for _ in range(n):
                    lock.rollback_ts.append(codec.decode_u64(b, off))
                    off += 8
            else:
                raise ValueError(f"unknown lock tag {tag:#x}")
        return lock

    def is_pessimistic(self) -> bool:
        return self.lock_type == LockType.PESSIMISTIC

    def is_visible_to(self, read_ts: int, bypass_locks: frozenset[int] = frozenset()) -> bool:
        """True if a read at ``read_ts`` is NOT blocked by this lock.

        Mirrors ``Lock::check_ts_conflict`` (lock.rs:192): Lock/Pessimistic
        locks never block reads; a read below the lock ts passes; MAX_TS reads
        block (latest read must see pending writes) unless bypassed.
        """
        if self.lock_type in (LockType.LOCK, LockType.PESSIMISTIC):
            return True
        if self.ts > read_ts:
            return True
        if self.ts in bypass_locks:
            return True
        if self.min_commit_ts > read_ts:
            return True
        return False


# ---------------------------------------------------------------------------
# Mutations  (txn_types/src/types.rs:258)
# ---------------------------------------------------------------------------

class MutationType(enum.Enum):
    PUT = "put"
    DELETE = "delete"
    LOCK = "lock"
    INSERT = "insert"  # put + must-not-exist constraint
    CHECK_NOT_EXISTS = "check_not_exists"


@dataclass
class Mutation:
    mutation_type: MutationType
    key: Key
    value: bytes | None = None

    @classmethod
    def put(cls, key: Key, value: bytes) -> "Mutation":
        return cls(MutationType.PUT, key, value)

    @classmethod
    def delete(cls, key: Key) -> "Mutation":
        return cls(MutationType.DELETE, key)

    @classmethod
    def lock(cls, key: Key) -> "Mutation":
        return cls(MutationType.LOCK, key)

    @classmethod
    def insert(cls, key: Key, value: bytes) -> "Mutation":
        return cls(MutationType.INSERT, key, value)

    @classmethod
    def check_not_exists(cls, key: Key) -> "Mutation":
        return cls(MutationType.CHECK_NOT_EXISTS, key)

    def should_not_exists(self) -> bool:
        return self.mutation_type in (MutationType.INSERT, MutationType.CHECK_NOT_EXISTS)

    def lock_type(self) -> LockType:
        return {
            MutationType.PUT: LockType.PUT,
            MutationType.INSERT: LockType.PUT,
            MutationType.DELETE: LockType.DELETE,
            MutationType.LOCK: LockType.LOCK,
            MutationType.CHECK_NOT_EXISTS: LockType.LOCK,
        }[self.mutation_type]


class TsSet:
    """Cheap set of timestamps for bypass/committing lock checks (timestamp.rs:111)."""

    __slots__ = ("_set",)

    def __init__(self, tss: list[int] | None = None):
        self._set = frozenset(tss or ())

    def contains(self, ts: int) -> bool:
        return ts in self._set

    def as_frozenset(self) -> frozenset[int]:
        return self._set
