"""Per-key latches serializing conflicting write commands.

Re-expression of ``src/storage/txn/latch.rs:141,162,188``: commands acquire a
latch per touched key (hashed into slots); a command runs only when it is at
the front of every slot's queue, guaranteeing FIFO fairness per key and
atomic read-modify-write across its snapshot+write window.
"""

from __future__ import annotations

import threading
from collections import deque


class Latches:
    def __init__(self, size: int = 256):
        self.size = size
        self._slots: list[deque[int]] = [deque() for _ in range(size)]
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._next_cid = 0

    def gen_cid(self) -> int:
        with self._mu:
            self._next_cid += 1
            return self._next_cid

    def _slot_ids(self, keys: list[bytes]) -> list[int]:
        return sorted({hash(k) % self.size for k in keys})

    def acquire_all(self, cid: int) -> list[int]:
        """Exclusive acquisition of EVERY slot — range commands (flashback)
        that must serialize against all per-key writers."""
        return self._acquire_slots(cid, list(range(self.size)))

    def acquire(self, cid: int, keys: list[bytes]) -> list[int]:
        """Enqueue cid on each slot and block until it is at every front."""
        return self._acquire_slots(cid, self._slot_ids(keys))

    def _acquire_slots(self, cid: int, slots: list[int]) -> list[int]:
        with self._cv:
            for s in slots:
                self._slots[s].append(cid)
            while not all(self._slots[s][0] == cid for s in slots):
                self._cv.wait()
        return slots

    def try_acquire(self, cid: int, keys: list[bytes]) -> tuple[bool, list[int]]:
        """Non-blocking: enqueue and report whether all fronts are ours."""
        slots = self._slot_ids(keys)
        with self._cv:
            for s in slots:
                if cid not in self._slots[s]:
                    self._slots[s].append(cid)
            return all(self._slots[s][0] == cid for s in slots), slots

    def release(self, cid: int, slots: list[int]) -> None:
        with self._cv:
            for s in slots:
                if self._slots[s] and self._slots[s][0] == cid:
                    self._slots[s].popleft()
                else:
                    try:
                        self._slots[s].remove(cid)
                    except ValueError:
                        pass
            self._cv.notify_all()
