"""Per-key latches with wake-up chains serializing conflicting commands.

Re-expression of ``src/storage/txn/latch.rs:141,162,188``: each touched key
hashes into a slot holding a FIFO queue of command ids.  A command owns the
latch set once it is at the front of every slot it enqueued on.  Acquisition
is NON-BLOCKING: a command that is not at every front parks, and the
releasing command's ``release()`` returns the ids that just completed their
acquisition — the wake-up chain the scheduler uses to re-schedule parked
commands onto its pool (scheduler.rs release_lock → try_to_wake_up).  No
thread ever sleeps inside the latch table.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass

from ...analysis.sanitizer import make_lock


@dataclass
class _Waiting:
    """A parked command: which slots it needs and how many fronts it holds."""

    slots: list[int]
    fronts: int = 0
    payload: object = None  # the scheduler's task, handed back at wake-up


class Latches:
    def __init__(self, size: int = 256):
        self.size = size
        self._slots: list[deque[int]] = [deque() for _ in range(size)]
        self._mu = make_lock("txn.latches")
        self._cids = itertools.count(1)
        self._waiting: dict[int, _Waiting] = {}

    def gen_cid(self) -> int:
        return next(self._cids)

    def slot_ids(self, keys: list[bytes]) -> list[int]:
        """The slots a key set hashes to — exposed so a caller can record
        them on its task BEFORE publishing the task as an acquire payload
        (the wake-up chain may run the task the instant the table sees it)."""
        return sorted({hash(k) % self.size for k in keys})

    _slot_ids = slot_ids

    def acquire(self, cid: int, keys: list[bytes], payload=None):
        """Enqueue on every slot for ``keys``.  Returns ``(granted, slots)``:
        granted means the command is at every front and may run NOW;
        otherwise it is parked and its payload will be handed back by the
        ``release()`` call that completes its acquisition."""
        return self._acquire_slots(cid, self._slot_ids(keys), payload)

    def acquire_all(self, cid: int, payload=None):
        """Exclusive acquisition of EVERY slot — range commands (flashback)
        that must serialize against all per-key writers."""
        return self._acquire_slots(cid, list(range(self.size)), payload)

    def acquire_blocking(self, cid: int, keys: list[bytes]) -> list[int]:
        """Block the calling thread until the latches are owned — for users
        outside the sched pool (raw CAS, TTL sweeps) that run on their own
        thread and want the old blocking semantics."""
        ev = threading.Event()
        granted, slots = self.acquire(cid, keys, payload=ev)
        if not granted:
            ev.wait()
        return slots

    def acquire_slots(self, cid: int, slots: list[int], payload=None):
        """Acquire pre-computed slots (from ``slot_ids``)."""
        return self._acquire_slots(cid, slots, payload)

    def _acquire_slots(self, cid: int, slots: list[int], payload):
        with self._mu:
            fronts = 0
            for s in slots:
                self._slots[s].append(cid)
                if self._slots[s][0] == cid:
                    fronts += 1
            if fronts == len(slots):
                return True, slots
            self._waiting[cid] = _Waiting(slots, fronts, payload)
            return False, slots

    def release(self, cid: int, slots: list[int]) -> list[object]:
        """Remove ``cid`` (which owned every slot in ``slots``) and return the
        payloads of commands whose acquisition just completed — the wake-up
        chain.  The caller re-schedules them; nothing blocks in here."""
        woken: list[object] = []
        with self._mu:
            self._release_locked(cid, slots, woken)
        return woken

    def release_many(self, pairs: list[tuple[int, list[int]]]) -> list[object]:
        """Release a batch of owners in ONE lock round — the group-commit
        path's sweep (scheduler._execute_group): K releases under one mutex
        acquisition instead of K.  Wake-up semantics are identical to K
        sequential ``release`` calls in ``pairs`` order."""
        woken: list[object] = []
        with self._mu:
            for cid, slots in pairs:
                self._release_locked(cid, slots, woken)
        return woken

    def _release_locked(self, cid: int, slots: list[int], woken: list) -> None:
        # a parked command being torn down (scheduler shutdown) must also
        # drop its _waiting record — with its cid purged from every queue
        # no future release could ever complete the acquisition
        self._waiting.pop(cid, None)
        for s in slots:
            q = self._slots[s]
            if q and q[0] == cid:
                q.popleft()
            else:  # defensive: command errored before owning this slot
                try:
                    q.remove(cid)
                except ValueError:
                    pass
                continue  # no new front exposed
            if q:
                w = self._waiting.get(q[0])
                if w is not None:
                    w.fronts += 1
                    if w.fronts == len(w.slots):
                        del self._waiting[q[0]]
                        if isinstance(w.payload, threading.Event):
                            w.payload.set()  # blocking acquirer wakes here
                        else:
                            woken.append(w.payload)
