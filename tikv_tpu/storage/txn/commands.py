"""Transaction commands — one class per scheduler command.

Re-expression of ``src/storage/txn/commands/`` (one file per command there:
prewrite, commit, acquire_pessimistic_lock, check_txn_status,
check_secondary_locks, cleanup, rollback, pessimistic_rollback, resolve_lock,
txn_heart_beat, mvcc_by_key/start_ts, compare_and_swap, atomic_store).

Each command declares the keys it must latch and a ``process_write(snapshot)``
producing (WriteBatch, result) — executed by the Scheduler under latches.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..engine import Snapshot
from ..mvcc.reader import IsolationLevel, KeyIsLockedError, MvccReader, WriteConflictError
from ..mvcc.txn import (
    MvccTxn,
    PrewriteContext,
    TxnError,
    TxnStatus,
    TxnStatusKind,
    acquire_pessimistic_lock,
    check_txn_status,
    commit_key,
    prewrite_key,
    rollback_key,
)
from ..txn_types import Key, Lock, Mutation, WriteType


class Command:
    # group commit eligibility (scheduler._collect_group_locked): a
    # groupable command reads/writes ONLY its own latched keys, so any set
    # of queued (latch-granted, hence key-disjoint) groupable commands
    # composes into one snapshot + one engine WriteBatch with effects
    # identical to back-to-back execution.  Range/scan commands
    # (ResolveLock-without-keys, Flashback) must stay non-groupable.
    groupable = False

    def latch_keys(self) -> list[bytes]:
        raise NotImplementedError

    def process_write(self, snapshot: Snapshot):
        """Returns (MvccTxn, result)."""
        raise NotImplementedError


@dataclass
class Prewrite(Command):
    mutations: list[Mutation]
    primary: bytes
    start_ts: int
    lock_ttl: int = 3000
    txn_size: int = 0
    min_commit_ts: int = 0
    use_async_commit: bool = False
    secondaries: list[bytes] = field(default_factory=list)
    # pessimistic variant: per-mutation flags, aligned with mutations
    is_pessimistic: bool = False
    pessimistic_flags: list[bool] = field(default_factory=list)
    for_update_ts: int = 0

    groupable = True  # touches only its latched keys (group commit)

    def latch_keys(self) -> list[bytes]:
        return [m.key.encoded for m in self.mutations]

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        ctx = PrewriteContext(
            primary=self.primary,
            start_ts=self.start_ts,
            lock_ttl=self.lock_ttl,
            txn_size=self.txn_size,
            min_commit_ts=self.min_commit_ts,
            use_async_commit=self.use_async_commit,
            secondaries=self.secondaries,
            is_pessimistic=self.is_pessimistic,
        )
        min_commit_ts = 0
        errors: list[Exception] = []
        for i, m in enumerate(self.mutations):
            flag = self.pessimistic_flags[i] if i < len(self.pessimistic_flags) else False
            try:
                ts = prewrite_key(txn, reader, m, ctx, is_pessimistic_lock=flag)
                min_commit_ts = max(min_commit_ts, ts)
            except (KeyIsLockedError, WriteConflictError, TxnError) as e:
                errors.append(e)
        if errors:
            # keys that prewrote fine stay locked (the reference persists the
            # successful locks alongside the KeyError vec; the client retries
            # or resolves them) — so the txn buffer is NOT discarded
            return txn, {"errors": errors, "min_commit_ts": min_commit_ts}
        return txn, {"min_commit_ts": min_commit_ts}


@dataclass
class Commit(Command):
    keys: list[Key]
    start_ts: int
    commit_ts: int

    groupable = True  # touches only its latched keys (group commit)

    def latch_keys(self) -> list[bytes]:
        return [k.encoded for k in self.keys]

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        for k in self.keys:
            commit_key(txn, reader, k, self.start_ts, self.commit_ts)
        return txn, {"commit_ts": self.commit_ts}


@dataclass
class Rollback(Command):
    keys: list[Key]
    start_ts: int

    def latch_keys(self) -> list[bytes]:
        return [k.encoded for k in self.keys]

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        for k in self.keys:
            rollback_key(txn, reader, k, self.start_ts)
        return txn, {}


@dataclass
class Cleanup(Command):
    """Rollback the primary if its TTL expired (or unconditionally when
    current_ts == 0) — commands/cleanup.rs.

    Deliberately rolls back async-commit locks too, matching the reference
    (actions/cleanup.rs calls rollback_lock with no use_async_commit check):
    Cleanup is the txn owner's own path, unlike CheckTxnStatus which other
    txns invoke and which must not roll back async-commit primaries."""

    key: Key
    start_ts: int
    current_ts: int

    def latch_keys(self) -> list[bytes]:
        return [self.key.encoded]

    def process_write(self, snapshot: Snapshot):
        from ..txn_types import ts_physical

        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        lock = reader.load_lock(self.key)
        if lock is not None and lock.ts == self.start_ts and self.current_ts:
            if ts_physical(self.current_ts) - ts_physical(self.start_ts) < lock.ttl:
                raise KeyIsLockedError(self.key.to_raw(), lock)
        rollback_key(txn, reader, self.key, self.start_ts, protect=True)
        return txn, {}


@dataclass
class AcquirePessimisticLock(Command):
    keys: list[tuple[Key, bool]]  # (key, should_not_exist)
    primary: bytes
    start_ts: int
    for_update_ts: int
    lock_ttl: int = 3000
    return_values: bool = False

    def latch_keys(self) -> list[bytes]:
        return [k.encoded for k, _ in self.keys]

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        values = []
        for k, sne in self.keys:
            v = acquire_pessimistic_lock(
                txn, reader, k, self.primary, self.start_ts, self.for_update_ts,
                ttl=self.lock_ttl, should_not_exist=sne,
            )
            values.append(v)
        return txn, {"values": values if self.return_values else None}


@dataclass
class PessimisticRollback(Command):
    keys: list[Key]
    start_ts: int
    for_update_ts: int

    def latch_keys(self) -> list[bytes]:
        return [k.encoded for k in self.keys]

    def process_write(self, snapshot: Snapshot):
        from ..txn_types import LockType

        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        for k in self.keys:
            lock = reader.load_lock(k)
            if (
                lock is not None
                and lock.lock_type == LockType.PESSIMISTIC
                and lock.ts == self.start_ts
                and lock.for_update_ts <= self.for_update_ts
            ):
                txn.unlock_key(k)
        return txn, {}


@dataclass
class TxnHeartBeat(Command):
    primary_key: Key
    start_ts: int
    advise_ttl: int

    def latch_keys(self) -> list[bytes]:
        return [self.primary_key.encoded]

    def process_write(self, snapshot: Snapshot):
        from ..mvcc.txn import TxnLockNotFoundError

        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        lock = reader.load_lock(self.primary_key)
        if lock is None or lock.ts != self.start_ts:
            raise TxnLockNotFoundError(self.primary_key, self.start_ts)
        if self.advise_ttl > lock.ttl:
            lock.ttl = self.advise_ttl
            txn.put_lock(self.primary_key, lock)
        return txn, {"lock_ttl": lock.ttl}


@dataclass
class CheckTxnStatus(Command):
    primary_key: Key
    lock_ts: int
    caller_start_ts: int
    current_ts: int
    rollback_if_not_exist: bool = False
    force_sync_commit: bool = False

    def latch_keys(self) -> list[bytes]:
        return [self.primary_key.encoded]

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.lock_ts)
        reader = MvccReader(snapshot)
        status = check_txn_status(
            txn, reader, self.primary_key, self.lock_ts,
            self.caller_start_ts, self.current_ts, self.rollback_if_not_exist,
            force_sync_commit=self.force_sync_commit,
        )
        return txn, {"status": status}


@dataclass
class CheckSecondaryLocks(Command):
    """Async-commit: determine secondaries' fate (commands/check_secondary_locks.rs)."""

    keys: list[Key]
    start_ts: int

    def latch_keys(self) -> list[bytes]:
        return [k.encoded for k in self.keys]

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        locks: list[Lock] = []
        commit_ts = 0
        for k in self.keys:
            lock = reader.load_lock(k)
            if lock is not None and lock.ts == self.start_ts:
                if lock.lock_type.name == "PESSIMISTIC":
                    # pessimistic lock can't decide a commit: roll it back
                    rollback_key(txn, reader, k, self.start_ts, protect=True)
                else:
                    locks.append(lock)
                continue
            found = False
            for cts, w in reader.get_txn_commit_record(k, self.start_ts):
                found = True
                if w.write_type != WriteType.ROLLBACK:
                    commit_ts = max(commit_ts, cts)
            if not found:
                rollback_key(txn, reader, k, self.start_ts, protect=True)
                return txn, {"locks": [], "commit_ts": 0}
        return txn, {"locks": locks, "commit_ts": commit_ts}


@dataclass
class ResolveLock(Command):
    """Commit or roll back all keys of txn start_ts per the primary's fate
    (commands/resolve_lock.rs; the lite variant takes explicit keys)."""

    start_ts: int
    commit_ts: int  # 0 = roll back
    keys: list[Key] | None = None  # None = scan all locks of this txn

    def latch_keys(self) -> list[bytes]:
        return [k.encoded for k in self.keys] if self.keys else []

    def process_write(self, snapshot: Snapshot):
        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        keys = self.keys
        if keys is None:
            keys = [
                k for k, lock in reader.scan_locks(None, None, lambda l: l.ts == self.start_ts)
            ]
        for k in keys:
            if self.commit_ts:
                commit_key(txn, reader, k, self.start_ts, self.commit_ts)
            else:
                rollback_key(txn, reader, k, self.start_ts)
        return txn, {"resolved": len(keys)}


@dataclass
class FlashbackToVersion(Command):
    """Restore a key range to its state as of ``version``
    (commands/flashback_to_version.rs + flashback_to_version_read_phase.rs,
    folded into one command for the in-process scheduler): every key whose
    newest write landed after ``version`` gets a NEW record at ``commit_ts``
    reinstating the old value (or a DELETE if the key didn't exist) — MVCC
    history below ``commit_ts`` stays intact, so this is an append-only,
    replayable operation.  All locks in the range are cleared first, exactly
    like the reference's prepare phase."""

    version: int
    start_ts: int
    commit_ts: int
    start_key: Key | None = None
    end_key: Key | None = None

    # flashback's correctness depends on its snapshot being the write-time
    # state: take every latch slot (the reference serializes via an
    # exclusive prepare phase)
    exclusive = True

    def latch_keys(self) -> list[bytes]:
        return []

    def process_write(self, snapshot: Snapshot):
        from ..engine import CF_WRITE
        from ..txn_types import SHORT_VALUE_MAX_LEN, Write, split_ts

        txn = MvccTxn(self.start_ts)
        reader = MvccReader(snapshot)
        # 1. ROLL BACK every lock in range (flashback supersedes in-flight
        # txns): rollback_key also removes orphaned CF_DEFAULT prewrite
        # values and leaves a protected rollback marker so the superseded
        # txn cannot re-prewrite + commit after the flashback
        for k, lock in reader.scan_locks(self.start_key, self.end_key):
            rollback_key(txn, reader, k, lock.ts, protect=True)
        # 2. every user key with any write newer than `version` gets reset
        start_enc = self.start_key.encoded if self.start_key else b""
        end_enc = self.end_key.encoded if self.end_key else None
        changed = 0
        last_user: bytes | None = None
        for wkey, _wval in snapshot.scan_cf(CF_WRITE, start_enc, end_enc):
            user_enc, commit_ts = split_ts(wkey)
            if user_enc == last_user:
                continue  # CF_WRITE is newest-first per key
            last_user = user_enc
            if commit_ts >= self.commit_ts:
                # a write committed after our TSOs were fetched: the restore
                # record would be silently shadowed — fail loudly so the
                # client retries with fresh timestamps (the reference closes
                # this window with its blocking prepare phase)
                raise WriteConflictError(
                    Key.from_encoded(user_enc).to_raw(), self.start_ts, 0, commit_ts
                )
            if commit_ts <= self.version:
                continue  # newest write predates the flashback point: keep
            key = Key.from_encoded(user_enc)
            # RC isolation: in-range locks are being rolled back in this very
            # batch, so the snapshot's lock records must not abort the reads
            old_value = reader.get(key, self.version, isolation=IsolationLevel.RC)
            current = reader.get(key, self.start_ts, isolation=IsolationLevel.RC)
            if old_value == current:
                continue
            if old_value is None:
                txn.put_write(key, self.commit_ts, Write(WriteType.DELETE, self.start_ts))
            else:
                w = Write(WriteType.PUT, self.start_ts)
                if len(old_value) <= SHORT_VALUE_MAX_LEN:
                    w.short_value = old_value
                else:
                    txn.put_value(key, self.start_ts, old_value)
                txn.put_write(key, self.commit_ts, w)
            changed += 1
        return txn, {"flashback_keys": changed}
