"""Txn command scheduler: latches → snapshot → process_write → engine write.

Re-expression of ``src/storage/txn/scheduler.rs:277`` (run_cmd:333,
schedule_command:353, execute:413, process_write:683): commands serialize on
per-key latches, execute against a fresh snapshot, and their WriteBatch goes
through the Engine; latches release on completion and queued commands wake.

The reference runs this over a sched thread pool; here execution is
synchronous per call (thread-safe — callers may be many threads), which keeps
the same ordering guarantees with Python-level simplicity.
"""

from __future__ import annotations

from ...util.failpoint import fail_point
from ..kv import Engine
from .commands import Command
from .latches import Latches


class Scheduler:
    def __init__(self, engine: Engine, concurrency_manager=None, latch_slots: int = 256):
        self.engine = engine
        self.latches = Latches(latch_slots)
        self.cm = concurrency_manager

    def run_command(self, cmd: Command, ctx: dict | None = None):
        cid = self.latches.gen_cid()
        if getattr(cmd, "exclusive", False):
            # range commands whose snapshot must BE the write-time state
            # (flashback) take every latch slot — full mutual exclusion
            slots = self.latches.acquire_all(cid)
        else:
            slots = self.latches.acquire(cid, cmd.latch_keys())
        try:
            fail_point("scheduler_async_snapshot")
            snapshot = self.engine.snapshot(ctx)
            txn, result = cmd.process_write(snapshot)
            fail_point("scheduler_before_write")
            if not txn.is_empty():
                self.engine.write(ctx, txn.wb)
            return result
        finally:
            self.latches.release(cid, slots)
