"""Txn command scheduler: latches → sched pool → process_write → engine.

Re-expression of ``src/storage/txn/scheduler.rs:277-683`` (run_cmd:333,
schedule_command:353, execute:413, process_write:683, release_lock wake-up
chains, too_busy flow control):

* commands try their per-key latches NON-blocking; a loser parks in the
  latch queue and is re-scheduled by the releasing command's wake-up chain —
  no pool thread ever sleeps holding nothing
* execution happens on a bounded sched pool (``sched-worker-N`` threads);
  high-priority commands jump the run queue (the reference's separate
  high-priority pool, expressed as strict two-level dispatch)
* flow control: when queued+running commands exceed
  ``pending_write_threshold``, new normal-priority commands fail fast with
  ``SchedTooBusy`` (scheduler.rs too_busy → ServerIsBusy) instead of growing
  the queue without bound; high-priority commands bypass the check
* GROUP COMMIT (docs/write_path.md): when a worker claims a groupable
  command (prewrite / commit), it also claims every other queued groupable
  command with the SAME engine context — queued tasks already hold their
  (pairwise-disjoint) latches, so the group runs off one snapshot, folds
  its mutations into ONE engine WriteBatch and pays ONE engine write (one
  raft propose→apply→ack round trip instead of one per command), then
  releases every member's latches in one sweep
* ``run_command`` stays a synchronous facade (submit + wait) so every
  existing caller keeps its ordering guarantees
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field

import time

from ...analysis.sanitizer import make_condition, make_lock
from ...util import error_code, trace
from ...util.failpoint import fail_point
from ...util.metrics import REGISTRY
from ..engine import WriteBatch
from ..kv import Engine
from .commands import Command

_SCHED_COMMANDS = REGISTRY.counter(
    "tikv_scheduler_commands_total", "Txn commands by type and outcome")
_SCHED_TOO_BUSY = REGISTRY.counter(
    "tikv_scheduler_too_busy_total",
    "Submissions rejected by write flow control (ServerIsBusy)")
_SCHED_GROUP_SIZE = REGISTRY.histogram(
    "tikv_scheduler_group_size",
    "Commands per scheduler engine write (group commit)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
from .latches import Latches

SCHED_TOO_BUSY = error_code.define(
    "KV:Storage:SchedTooBusy", "txn scheduler write queue is full"
)


class SchedTooBusy(Exception):
    """Raised at submission when the scheduler is over its pending-write
    threshold (the client should back off and retry — ServerIsBusy).
    ``retry_after_s`` hints when capacity is expected back; the shared
    retry policy (``util.retry``) sleeps at least that long."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        self.retry_after_s = retry_after_s
        super().__init__(msg)


error_code.register(SchedTooBusy, SCHED_TOO_BUSY)


@dataclass(eq=False)  # identity hash: tasks live in the inflight set
class _Task:
    cmd: Command
    ctx: dict | None
    cid: int
    high: bool
    slots: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    exc: BaseException | None = None
    # exactly-once completion: set (under the scheduler lock) by whichever of
    # a worker's _execute or shutdown's _fail_task gets the task first; the
    # loser must not touch latches/_inflight again
    claimed: bool = False
    # write-path observability (docs/tracing.md): submission time anchors
    # the latch/queue-wait phase; trace_ctx hands the submitter's span to
    # the worker thread that executes the command
    submit_t: float = 0.0
    trace_ctx: dict | None = None


class Scheduler:
    def __init__(
        self,
        engine: Engine,
        concurrency_manager=None,
        latch_slots: int = 256,
        pool_size: int = 4,
        pending_write_threshold: int = 256,
        group_commit_max: int = 16,
        slow_log=None,
    ):
        self.engine = engine
        self.latches = Latches(latch_slots)
        # write-path slow log (docs/tracing.md): slow txn commands land in
        # the same JSON-line sink shape as the coprocessor's SlowLog, with
        # the latch-wait / process / propose→apply phase breakdown and the
        # request's trace id.  copr.tracker imports only stdlib, so the
        # lazy import cannot cycle back into storage.
        if slow_log is None:
            from ...copr.tracker import SlowLog

            slow_log = SlowLog()
        self.slow_log = slow_log
        self.cm = concurrency_manager
        self.pool_size = pool_size
        self.pending_write_threshold = pending_write_threshold
        # group commit: max queued compatible commands coalesced into one
        # engine write (1 disables — every command pays its own round trip)
        self.group_commit_max = max(1, group_commit_max)
        self._mu = make_lock("txn.scheduler")
        self._ready = make_condition("txn.scheduler", self._mu)
        self._high: deque[_Task] = deque()
        self._normal: deque[_Task] = deque()
        self._inflight = 0  # submitted, not yet finished (queued or running)
        self._tasks: set = set()  # every inflight task, incl. latch-parked ones
        self._threads: list[threading.Thread] = []
        self._stopped = False
        # observability (scheduler.rs metrics role)
        self.stats = {"scheduled": 0, "woken": 0, "too_busy": 0}

    # --- submission ---------------------------------------------------------

    def run_command(self, cmd: Command, ctx: dict | None = None):
        """Synchronous facade: submit, wait, raise the command's error."""
        task = self.submit(cmd, ctx)
        task.done.wait()
        status = "done" if task.exc is None else "error"
        _SCHED_COMMANDS.inc(type=type(cmd).__name__, status=status)
        if task.exc is not None:
            raise task.exc
        return task.result

    def submit(self, cmd: Command, ctx: dict | None = None) -> _Task:
        high = bool(ctx and ctx.get("priority") == "high")
        with self._mu:
            if self._stopped:
                raise RuntimeError("scheduler is stopped")
            if not high and self._inflight >= self.pending_write_threshold:
                self.stats["too_busy"] += 1
                _SCHED_TOO_BUSY.inc()
                raise SchedTooBusy(
                    f"{self._inflight} commands pending "
                    f"(threshold {self.pending_write_threshold})",
                    # drain hint: pending work over worker parallelism, at a
                    # nominal ~1ms per engine write round trip — floored at
                    # 1ms so the busy class's backoff stays hint-dominated
                    # (util.retry; docs/robustness.md "Overload")
                    retry_after_s=max(
                        0.001 * self._inflight / max(self.pool_size, 1),
                        0.001),
                )
            self._inflight += 1
            self._ensure_threads()
        task = None
        try:
            cid = self.latches.gen_cid()
            task = _Task(cmd, ctx, cid, high)
            task.submit_t = time.perf_counter()
            task.trace_ctx = trace.current_context()
            # slots go on the task BEFORE the latch table sees it: a parked
            # task can be woken and executed the moment acquire publishes it,
            # and release() needs task.slots populated by then
            if getattr(cmd, "exclusive", False):
                task.slots = list(range(self.latches.size))
            else:
                task.slots = self.latches.slot_ids(cmd.latch_keys())
            with self._mu:
                self._tasks.add(task)
            granted, _ = self.latches.acquire_slots(cid, task.slots, task)
        except BaseException:
            with self._mu:
                self._inflight -= 1  # never reached _execute's decrement
                if task is not None:
                    self._tasks.discard(task)
            raise
        with self._mu:
            failed_by_stop = task.claimed
        if failed_by_stop:
            # stop()'s drain claimed the task between _tasks.add and the
            # latch acquisition above: the dead cid is now queued in the
            # latch table with nobody left to release it — undo that here
            # (release is idempotent for a cid stop already purged)
            for t in self.latches.release(cid, task.slots):
                self._enqueue(t)
        elif granted:
            self._enqueue(task)
        # else: parked — some release() will hand the task back
        return task

    def _enqueue(self, task: _Task) -> None:
        with self._mu:
            if self._stopped:
                # no workers remain to run it; fail it so waiters unblock
                stopped = True
            else:
                stopped = False
                (self._high if task.high else self._normal).append(task)
                self.stats["scheduled"] += 1
                self._ready.notify()
        if stopped:
            self._fail_task(task, RuntimeError("scheduler stopped"))

    def _ensure_threads(self) -> None:
        # lazily grown to pool_size; caller holds self._mu
        while len(self._threads) < self.pool_size and not self._stopped:
            t = threading.Thread(
                target=self._worker,
                name=f"sched-worker-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    # --- execution ----------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._ready:
                while not self._high and not self._normal and not self._stopped:
                    self._ready.wait()
                if self._stopped and not self._high and not self._normal:
                    return
                task = (self._high or self._normal).popleft()
                if task.claimed:  # shutdown already failed it
                    continue
                task.claimed = True
                group = self._collect_group_locked(task)
            if group:
                self._execute_group([task] + group)
            else:
                self._execute(task)

    def _collect_group_locked(self, leader: _Task) -> list[_Task]:
        """Claim queued commands compatible with ``leader`` for one group
        commit (caller holds the scheduler lock).  Compatible = a groupable
        command type (prewrite/commit) against the SAME engine context —
        the one raft proposal the group folds into must route to one region.
        Every queued task already owns its latches, and two tasks sharing a
        latch slot can never be queued together, so group members touch
        pairwise-disjoint keys and compose into one WriteBatch exactly as
        they would execute back to back."""
        if self.group_commit_max <= 1 or not getattr(leader.cmd, "groupable", False):
            return []
        picked: list[_Task] = []
        for q in (self._high, self._normal):
            if len(picked) + 1 >= self.group_commit_max:
                break
            kept: list[_Task] = []
            while q and len(picked) + 1 < self.group_commit_max:
                t = q.popleft()
                if t.claimed:
                    continue  # shutdown already failed it (worker-loop rule)
                if getattr(t.cmd, "groupable", False) and t.ctx == leader.ctx:
                    t.claimed = True
                    picked.append(t)
                else:
                    kept.append(t)
            for t in reversed(kept):  # unpicked keep their FIFO positions
                q.appendleft(t)
        return picked

    def _execute(self, task: _Task) -> None:
        t_claim = time.perf_counter()
        propose_s = 0.0
        try:
            # pool-boundary handoff: worker-side phases land in the
            # submitting request's trace (docs/tracing.md)
            with trace.attach(task.trace_ctx):
                trace.record("txn.latch_wait", task.submit_t, t_claim,
                             cmd=type(task.cmd).__name__)
                fail_point("scheduler_async_snapshot")
                with trace.span("txn.process_write"):
                    snapshot = self.engine.snapshot(task.ctx)
                    txn, result = task.cmd.process_write(snapshot)
                t_proc = time.perf_counter()
                fail_point("scheduler_before_write")
                if not txn.is_empty():
                    # observed per actual engine write: the histogram's count
                    # IS the raft-proposal rate, its mean the amortization
                    # factor
                    _SCHED_GROUP_SIZE.observe(1)
                    self.engine.write(task.ctx, txn.wb)
                    propose_s = time.perf_counter() - t_proc
            task.result = result
        except BaseException as exc:  # surfaced to the submitting thread
            task.exc = exc
        finally:
            self._observe_slow([task], t_claim, propose_s, group=1)
            self._finish(task)

    def _execute_group(self, tasks: list[_Task]) -> None:
        """Group commit: one snapshot, each command's process_write buffered,
        ONE engine write for every mutation (scheduler.rs would pay one
        propose→apply→ack round trip per command here).  Per-command errors
        (lock conflicts, txn state) fail only their own task; a write
        failure fails exactly the tasks whose mutations rode the batch."""
        ctx = tasks[0].ctx
        contributed: list[_Task] = []
        t_claim = time.perf_counter()
        propose_s = 0.0
        # group-commit fold phases ride the LEADER's trace (the group's
        # other members link the shared write via their slow-log entries):
        # one fold span, one propose→apply span, N latch-wait records
        with trace.attach(tasks[0].trace_ctx):
            for t in tasks:
                trace.remote_span(t.trace_ctx, "txn.latch_wait",
                                  start=t.submit_t, end=t_claim,
                                  cmd=type(t.cmd).__name__,
                                  group_size=len(tasks))
            try:
                fail_point("scheduler_async_snapshot")
                snapshot = self.engine.snapshot(ctx)
            except BaseException as exc:
                for t in tasks:
                    t.exc = exc
            else:
                wb = WriteBatch()
                with trace.span("txn.group_fold", group_size=len(tasks)):
                    for t in tasks:
                        try:
                            txn, result = t.cmd.process_write(snapshot)
                            t.result = result
                            if not txn.is_empty():
                                contributed.append(t)
                                wb.ops.extend(txn.wb.ops)
                        except BaseException as exc:
                            t.exc = exc
                t_proc = time.perf_counter()
                try:
                    fail_point("scheduler_before_write")
                    if wb.ops:
                        # commands whose mutations actually rode this ONE write
                        _SCHED_GROUP_SIZE.observe(len(contributed))
                        self.engine.write(ctx, wb)
                        propose_s = time.perf_counter() - t_proc
                except BaseException as exc:
                    for t in contributed:
                        t.result = None
                        t.exc = exc
        self._observe_slow(tasks, t_claim, propose_s, group=len(tasks))
        # one release sweep for the whole group: K latch releases under a
        # single latch-table lock round (latches.release_many)
        woken = self.latches.release_many([(t.cid, t.slots) for t in tasks])
        with self._mu:
            self._inflight -= len(tasks)
            for t in tasks:
                self._tasks.discard(t)
            self.stats["woken"] += len(woken)
        for w in woken:
            self._enqueue(w)
        for t in tasks:
            t.done.set()

    def _observe_slow(self, tasks: list[_Task], t_claim: float,
                      propose_s: float, group: int) -> None:
        """Slow-log parity for writes (docs/tracing.md): any command whose
        end-to-end latency crosses the sink's threshold records its phase
        breakdown — latch/queue wait, process_write, raft propose→apply —
        plus its trace id, in the same JSON-line shape as the coprocessor
        slow log."""
        now = time.perf_counter()
        threshold = self.slow_log.threshold_s
        for t in tasks:
            if t.submit_t <= 0.0:
                continue
            total = now - t.submit_t
            if total < threshold:
                continue
            wait = max(t_claim - t.submit_t, 0.0)
            fields = {
                "latch_wait_ms": round(wait * 1000, 3),
                "process_ms": round(max(total - wait - propose_s, 0.0) * 1000, 3),
                "propose_apply_ms": round(propose_s * 1000, 3),
                "total_ms": round(total * 1000, 3),
                "group_size": group,
                "status": "error" if t.exc is not None else "done",
                # observatory-parity fields (docs/observatory.md): the write
                # path's serving shape and command signature, so copr and
                # txn slow-log entries carry the same pivot keys
                "path": "txn_group" if group > 1 else "txn",
                "plan_sig": f"txn:{type(t.cmd).__name__}",
            }
            if t.trace_ctx and t.trace_ctx.get("trace_id"):
                fields["trace_id"] = t.trace_ctx["trace_id"]
            self.slow_log.record(f"txn {type(t.cmd).__name__}", fields)

    def _finish(self, task: _Task) -> None:
        woken = self.latches.release(task.cid, task.slots)
        with self._mu:
            self._inflight -= 1
            self._tasks.discard(task)
            self.stats["woken"] += len(woken)
        for t in woken:
            self._enqueue(t)
        task.done.set()

    def _fail_task(self, task: _Task, exc: BaseException) -> None:
        with self._mu:
            if task.claimed:  # a worker owns it (or it already finished)
                return
            task.claimed = True
            self._inflight -= 1
            self._tasks.discard(task)
        woken = self.latches.release(task.cid, task.slots)
        task.exc = exc
        task.done.set()
        for t in woken:
            self._enqueue(t)  # re-entrant: fails the chain one by one

    def stop(self) -> None:
        with self._mu:
            self._stopped = True
            self._ready.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        # Fail whatever is still queued or parked in the latch table, so no
        # caller blocked in run_command's done.wait() hangs past shutdown.
        # Tasks a live worker claimed are left alone — the worker's _execute
        # completes them with their real outcome.
        while True:
            with self._mu:
                self._high.clear()
                self._normal.clear()
                task = next((t for t in self._tasks if not t.claimed), None)
            if task is None:
                break
            self._fail_task(task, RuntimeError("scheduler stopped"))
