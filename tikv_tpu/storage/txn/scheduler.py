"""Txn command scheduler: latches → sched pool → process_write → engine.

Re-expression of ``src/storage/txn/scheduler.rs:277-683`` (run_cmd:333,
schedule_command:353, execute:413, process_write:683, release_lock wake-up
chains, too_busy flow control):

* commands try their per-key latches NON-blocking; a loser parks in the
  latch queue and is re-scheduled by the releasing command's wake-up chain —
  no pool thread ever sleeps holding nothing
* execution happens on a bounded sched pool (``sched-worker-N`` threads);
  high-priority commands jump the run queue (the reference's separate
  high-priority pool, expressed as strict two-level dispatch)
* flow control: when queued+running commands exceed
  ``pending_write_threshold``, new normal-priority commands fail fast with
  ``SchedTooBusy`` (scheduler.rs too_busy → ServerIsBusy) instead of growing
  the queue without bound; high-priority commands bypass the check
* ``run_command`` stays a synchronous facade (submit + wait) so every
  existing caller keeps its ordering guarantees
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field

from ...util import error_code
from ...util.failpoint import fail_point
from ...util.metrics import REGISTRY
from ..kv import Engine
from .commands import Command

_SCHED_COMMANDS = REGISTRY.counter(
    "tikv_scheduler_commands_total", "Txn commands by type and outcome")
from .latches import Latches

SCHED_TOO_BUSY = error_code.define(
    "KV:Storage:SchedTooBusy", "txn scheduler write queue is full"
)


class SchedTooBusy(Exception):
    """Raised at submission when the scheduler is over its pending-write
    threshold (the client should back off and retry — ServerIsBusy)."""


error_code.register(SchedTooBusy, SCHED_TOO_BUSY)


@dataclass(eq=False)  # identity hash: tasks live in the inflight set
class _Task:
    cmd: Command
    ctx: dict | None
    cid: int
    high: bool
    slots: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    exc: BaseException | None = None
    # exactly-once completion: set (under the scheduler lock) by whichever of
    # a worker's _execute or shutdown's _fail_task gets the task first; the
    # loser must not touch latches/_inflight again
    claimed: bool = False


class Scheduler:
    def __init__(
        self,
        engine: Engine,
        concurrency_manager=None,
        latch_slots: int = 256,
        pool_size: int = 4,
        pending_write_threshold: int = 256,
    ):
        self.engine = engine
        self.latches = Latches(latch_slots)
        self.cm = concurrency_manager
        self.pool_size = pool_size
        self.pending_write_threshold = pending_write_threshold
        self._mu = threading.Lock()
        self._ready = threading.Condition(self._mu)
        self._high: deque[_Task] = deque()
        self._normal: deque[_Task] = deque()
        self._inflight = 0  # submitted, not yet finished (queued or running)
        self._tasks: set = set()  # every inflight task, incl. latch-parked ones
        self._threads: list[threading.Thread] = []
        self._stopped = False
        # observability (scheduler.rs metrics role)
        self.stats = {"scheduled": 0, "woken": 0, "too_busy": 0}

    # --- submission ---------------------------------------------------------

    def run_command(self, cmd: Command, ctx: dict | None = None):
        """Synchronous facade: submit, wait, raise the command's error."""
        task = self.submit(cmd, ctx)
        task.done.wait()
        status = "done" if task.exc is None else "error"
        _SCHED_COMMANDS.inc(type=type(cmd).__name__, status=status)
        if task.exc is not None:
            raise task.exc
        return task.result

    def submit(self, cmd: Command, ctx: dict | None = None) -> _Task:
        high = bool(ctx and ctx.get("priority") == "high")
        with self._mu:
            if self._stopped:
                raise RuntimeError("scheduler is stopped")
            if not high and self._inflight >= self.pending_write_threshold:
                self.stats["too_busy"] += 1
                raise SchedTooBusy(
                    f"{self._inflight} commands pending "
                    f"(threshold {self.pending_write_threshold})"
                )
            self._inflight += 1
            self._ensure_threads()
        task = None
        try:
            cid = self.latches.gen_cid()
            task = _Task(cmd, ctx, cid, high)
            # slots go on the task BEFORE the latch table sees it: a parked
            # task can be woken and executed the moment acquire publishes it,
            # and release() needs task.slots populated by then
            if getattr(cmd, "exclusive", False):
                task.slots = list(range(self.latches.size))
            else:
                task.slots = self.latches.slot_ids(cmd.latch_keys())
            with self._mu:
                self._tasks.add(task)
            granted, _ = self.latches.acquire_slots(cid, task.slots, task)
        except BaseException:
            with self._mu:
                self._inflight -= 1  # never reached _execute's decrement
                if task is not None:
                    self._tasks.discard(task)
            raise
        with self._mu:
            failed_by_stop = task.claimed
        if failed_by_stop:
            # stop()'s drain claimed the task between _tasks.add and the
            # latch acquisition above: the dead cid is now queued in the
            # latch table with nobody left to release it — undo that here
            # (release is idempotent for a cid stop already purged)
            for t in self.latches.release(cid, task.slots):
                self._enqueue(t)
        elif granted:
            self._enqueue(task)
        # else: parked — some release() will hand the task back
        return task

    def _enqueue(self, task: _Task) -> None:
        with self._mu:
            if self._stopped:
                # no workers remain to run it; fail it so waiters unblock
                stopped = True
            else:
                stopped = False
                (self._high if task.high else self._normal).append(task)
                self.stats["scheduled"] += 1
                self._ready.notify()
        if stopped:
            self._fail_task(task, RuntimeError("scheduler stopped"))

    def _ensure_threads(self) -> None:
        # lazily grown to pool_size; caller holds self._mu
        while len(self._threads) < self.pool_size and not self._stopped:
            t = threading.Thread(
                target=self._worker,
                name=f"sched-worker-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    # --- execution ----------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._ready:
                while not self._high and not self._normal and not self._stopped:
                    self._ready.wait()
                if self._stopped and not self._high and not self._normal:
                    return
                task = (self._high or self._normal).popleft()
                if task.claimed:  # shutdown already failed it
                    continue
                task.claimed = True
            self._execute(task)

    def _execute(self, task: _Task) -> None:
        try:
            fail_point("scheduler_async_snapshot")
            snapshot = self.engine.snapshot(task.ctx)
            txn, result = task.cmd.process_write(snapshot)
            fail_point("scheduler_before_write")
            if not txn.is_empty():
                self.engine.write(task.ctx, txn.wb)
            task.result = result
        except BaseException as exc:  # surfaced to the submitting thread
            task.exc = exc
        finally:
            woken = self.latches.release(task.cid, task.slots)
            with self._mu:
                self._inflight -= 1
                self._tasks.discard(task)
                self.stats["woken"] += len(woken)
            for t in woken:
                self._enqueue(t)
            task.done.set()

    def _fail_task(self, task: _Task, exc: BaseException) -> None:
        with self._mu:
            if task.claimed:  # a worker owns it (or it already finished)
                return
            task.claimed = True
            self._inflight -= 1
            self._tasks.discard(task)
        woken = self.latches.release(task.cid, task.slots)
        task.exc = exc
        task.done.set()
        for t in woken:
            self._enqueue(t)  # re-entrant: fails the chain one by one

    def stop(self) -> None:
        with self._mu:
            self._stopped = True
            self._ready.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        # Fail whatever is still queued or parked in the latch table, so no
        # caller blocked in run_command's done.wait() hangs past shutdown.
        # Tasks a live worker claimed are left alone — the worker's _execute
        # completes them with their real outcome.
        while True:
            with self._mu:
                self._high.clear()
                self._normal.clear()
                task = next((t for t in self._tasks if not t.claimed), None)
            if task is None:
                break
            self._fail_task(task, RuntimeError("scheduler stopped"))
