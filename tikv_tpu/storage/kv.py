"""The Engine trait the transaction layer runs over.

Re-expression of ``components/tikv_kv`` (``src/lib.rs:155``): storage code
only needs two operations — get a consistent snapshot, and atomically apply a
WriteBatch ("modifies").  ``LocalEngine`` runs them against a local KvEngine
(the reference's ``RocksEngine`` standalone mode / ``BTreeEngine`` tests);
``RaftKv`` (tikv_tpu.raft.raftkv) routes them through raft consensus.
"""

from __future__ import annotations

import abc
from typing import Callable

from .btree_engine import BTreeEngine
from .engine import KvEngine, Snapshot, WriteBatch


class Engine(abc.ABC):
    @abc.abstractmethod
    def snapshot(self, ctx: dict | None = None) -> Snapshot: ...

    @abc.abstractmethod
    def write(self, ctx: dict | None, batch: WriteBatch) -> None: ...

    def async_snapshot(self, ctx: dict | None, cb: Callable[[Snapshot], None]) -> None:
        cb(self.snapshot(ctx))

    def async_write(self, ctx: dict | None, batch: WriteBatch, cb: Callable[[Exception | None], None]) -> None:
        try:
            self.write(ctx, batch)
            cb(None)
        except Exception as e:  # noqa: BLE001 — delivered to callback
            cb(e)


class LocalEngine(Engine):
    """Single-node engine: raft-free, direct writes (tikv_kv BTreeEngine /
    RocksEngine standalone)."""

    def __init__(self, kv: KvEngine | None = None):
        self.kv = kv or BTreeEngine()

    def snapshot(self, ctx: dict | None = None) -> Snapshot:
        return self.kv.snapshot()

    def write(self, ctx: dict | None, batch: WriteBatch) -> None:
        self.kv.write(batch)
