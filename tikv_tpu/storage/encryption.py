"""Encryption at rest: two-level keys + encrypting engine wrapper.

Re-expression of ``components/encryption`` (master_key/{file,mem}.rs,
manager/, crypter.rs, file_dict_file.rs): a master key encrypts rotating
*data keys*; every value is encrypted under the current data key with a
per-value random IV; the key dictionary itself is stored encrypted under the
master key.  The reference wires AES-CTR through OpenSSL into RocksDB's Env;
this build has no cipher library, so the stream cipher is a keyed BLAKE2b
keystream in counter mode with a BLAKE2b MAC (encrypt-then-MAC) — same
architecture, swappable primitive, honest about the difference.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import threading

from ..util import codec
from .engine import Cursor, KvEngine, Snapshot, WriteBatch

_BLOCK = 64  # blake2b digest size


def _keystream(key: bytes, iv: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        out += hashlib.blake2b(
            iv + counter.to_bytes(8, "big"), key=key, digest_size=_BLOCK
        ).digest()
        counter += 1
    return bytes(out[:n])


def _xor(data: bytes, stream: bytes) -> bytes:
    # big-int XOR: ~50x faster than a per-byte generator on large values
    n = len(data)
    return (
        int.from_bytes(data, "little") ^ int.from_bytes(stream[:n], "little")
    ).to_bytes(n, "little")


def seal(key: bytes, plaintext: bytes) -> bytes:
    """iv(16) | ciphertext | mac(16) — encrypt-then-MAC."""
    iv = os.urandom(16)
    ct = _xor(plaintext, _keystream(key, iv, len(plaintext)))
    mac = hmac.new(key, iv + ct, hashlib.blake2b).digest()[:16]
    return iv + ct + mac


def unseal(key: bytes, sealed: bytes) -> bytes:
    if len(sealed) < 32:
        raise ValueError("sealed blob too short")
    iv, ct, mac = sealed[:16], sealed[16:-16], sealed[-16:]
    want = hmac.new(key, iv + ct, hashlib.blake2b).digest()[:16]
    if not hmac.compare_digest(mac, want):
        raise ValueError("MAC mismatch: wrong key or corrupted data")
    return _xor(ct, _keystream(key, iv, len(ct)))


class MasterKey:
    """Master key backends (master_key/{file,mem}.rs)."""

    def __init__(self, key: bytes):
        if len(key) < 16:
            raise ValueError("master key must be at least 16 bytes")
        self.key = hashlib.blake2b(key, digest_size=32).digest()

    @classmethod
    def from_file(cls, path: str) -> "MasterKey":
        with open(path, "rb") as f:
            return cls(bytes.fromhex(f.read().strip().decode()))

    @classmethod
    def mem(cls, seed: bytes = b"test-master-key-0000") -> "MasterKey":
        return cls(seed)


class DataKeyManager:
    """Rotating data keys sealed under the master key (manager/)."""

    def __init__(self, master: MasterKey):
        self.master = master
        self._mu = threading.Lock()
        self.keys: dict[int, bytes] = {}
        self.current_id = 0
        self.rotate()

    def rotate(self) -> int:
        with self._mu:
            self.current_id += 1
            self.keys[self.current_id] = os.urandom(32)
            return self.current_id

    def current(self) -> tuple[int, bytes]:
        with self._mu:
            return self.current_id, self.keys[self.current_id]

    def by_id(self, key_id: int) -> bytes:
        with self._mu:
            k = self.keys.get(key_id)
        if k is None:
            raise ValueError(f"unknown data key {key_id}")
        return k

    def export_dict(self) -> bytes:
        """The encrypted key dictionary (file_dict_file.rs)."""
        with self._mu:
            out = bytearray()
            out += codec.encode_var_u64(self.current_id)
            out += codec.encode_var_u64(len(self.keys))
            for kid, key in sorted(self.keys.items()):
                out += codec.encode_var_u64(kid)
                out += codec.encode_compact_bytes(key)
        return seal(self.master.key, bytes(out))

    @classmethod
    def import_dict(cls, master: MasterKey, sealed: bytes) -> "DataKeyManager":
        raw = unseal(master.key, sealed)
        mgr = cls.__new__(cls)
        mgr.master = master
        mgr._mu = threading.Lock()
        mgr.keys = {}
        cur, off = codec.decode_var_u64(raw, 0)
        n, off = codec.decode_var_u64(raw, off)
        for _ in range(n):
            kid, off = codec.decode_var_u64(raw, off)
            key, off = codec.decode_compact_bytes(raw, off)
            mgr.keys[kid] = key
        mgr.current_id = cur
        return mgr


class EncryptedEngine(KvEngine):
    """Engine wrapper encrypting every VALUE at rest (keys stay plaintext for
    ordering, like the reference's file-level encryption leaves RocksDB key
    order intact).  Stored value = varint key_id | sealed(value)."""

    def __init__(self, inner: KvEngine, keys_mgr: DataKeyManager):
        self.inner = inner
        self.keys = keys_mgr

    def _enc(self, value: bytes) -> bytes:
        kid, key = self.keys.current()
        return codec.encode_var_u64(kid) + seal(key, value)

    def _dec(self, stored: bytes) -> bytes:
        kid, off = codec.decode_var_u64(stored, 0)
        return unseal(self.keys.by_id(kid), stored[off:])

    def write(self, batch: WriteBatch) -> None:
        enc = WriteBatch()
        for op, cf, key, val in batch.ops:
            if op == "put":
                enc.put_cf(cf, key, self._enc(val))
            elif op == "delete":
                enc.delete_cf(cf, key)
            else:
                enc.delete_range_cf(cf, key, val)
        self.inner.write(enc)

    def snapshot(self) -> "EncryptedSnapshot":
        return EncryptedSnapshot(self.inner.snapshot(), self)

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        v = self.inner.get_cf(cf, key)
        return None if v is None else self._dec(v)

    def scan_cf(self, cf, start, end, limit=None, reverse=False):
        for k, v in self.inner.scan_cf(cf, start, end, limit, reverse):
            yield k, self._dec(v)

    def bulk_load(self, cf: str, items):
        self.inner.bulk_load(cf, [(k, self._enc(v)) for k, v in items])


class _DecCursor(Cursor):
    def __init__(self, inner: Cursor, eng: EncryptedEngine):
        self._c = inner
        self._e = eng

    def seek(self, key):
        return self._c.seek(key)

    def seek_for_prev(self, key):
        return self._c.seek_for_prev(key)

    def seek_to_first(self):
        return self._c.seek_to_first()

    def seek_to_last(self):
        return self._c.seek_to_last()

    def next(self):
        return self._c.next()

    def prev(self):
        return self._c.prev()

    def valid(self):
        return self._c.valid()

    def key(self):
        return self._c.key()

    def value(self):
        return self._e._dec(self._c.value())


class EncryptedSnapshot(Snapshot):
    def __init__(self, inner: Snapshot, eng: EncryptedEngine):
        self._snap = inner
        self._e = eng

    def get_cf(self, cf, key):
        v = self._snap.get_cf(cf, key)
        return None if v is None else self._e._dec(v)

    def cursor_cf(self, cf, lower=None, upper=None):
        return _DecCursor(self._snap.cursor_cf(cf, lower, upper), self._e)
