"""Encryption at rest: AES-GCM, two-level keys, encrypting engine wrapper.

Re-expression of ``components/encryption`` (master_key/{file,mem}.rs,
manager/, crypter.rs, file_dict_file.rs): a master key seals rotating *data
keys*; every value is encrypted under the current data key with a random
per-value nonce; the key dictionary itself is persisted sealed under the
master key, so rotating the MASTER key only re-seals the dictionary — data
written under old data keys stays readable without rewriting a byte.  The
cipher is AES-256-GCM (the reference's crypter.rs AEAD choice) via the
``cryptography`` package, with a keyed-BLAKE2b AEAD fallback when that
package is absent (same architecture, honest about the primitive).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import threading

from ..util import codec
from .engine import Cursor, KvEngine, Snapshot, WriteBatch

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - baked into this image
    AESGCM = None

_METHOD_BLAKE2 = 0  # keyed-keystream + MAC fallback
_METHOD_AESGCM = 1  # AES-256-GCM (crypter.rs EncryptionMethod::Aes256Gcm)

_BLOCK = 64  # blake2b digest size


def _keystream(key: bytes, iv: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        out += hashlib.blake2b(
            iv + counter.to_bytes(8, "big"), key=key, digest_size=_BLOCK
        ).digest()
        counter += 1
    return bytes(out[:n])


def _xor(data: bytes, stream: bytes) -> bytes:
    # big-int XOR: ~50x faster than a per-byte generator on large values
    n = len(data)
    return (
        int.from_bytes(data, "little") ^ int.from_bytes(stream[:n], "little")
    ).to_bytes(n, "little")


def seal(key: bytes, plaintext: bytes) -> bytes:
    """method(1) | nonce | ciphertext+tag — AEAD under the given 32-byte key."""
    if AESGCM is not None:
        nonce = os.urandom(12)
        ct = AESGCM(key).encrypt(nonce, plaintext, None)
        return bytes([_METHOD_AESGCM]) + nonce + ct
    iv = os.urandom(16)
    ct = _xor(plaintext, _keystream(key, iv, len(plaintext)))
    mac = hmac.new(key, iv + ct, hashlib.blake2b).digest()[:16]
    return bytes([_METHOD_BLAKE2]) + iv + ct + mac


def unseal(key: bytes, sealed: bytes) -> bytes:
    """Inverse of :func:`seal`.

    Format note: the leading method byte was introduced before any release
    shipped; there is no deployed data in the legacy headerless ``iv|ct|mac``
    layout, so no fallback parse is attempted for it.
    """
    if not sealed:
        raise ValueError("empty sealed blob")
    method, body = sealed[0], sealed[1:]
    if method == _METHOD_AESGCM:
        if AESGCM is None:
            raise ValueError("AES-GCM sealed data but no cipher library")
        if len(body) < 12 + 16:
            raise ValueError("sealed blob too short")
        from cryptography.exceptions import InvalidTag

        try:
            return AESGCM(key).decrypt(body[:12], body[12:], None)
        except InvalidTag as e:
            raise ValueError("AEAD tag mismatch: wrong key or corrupted data") from e
    if method == _METHOD_BLAKE2:
        if len(body) < 32:
            raise ValueError("sealed blob too short")
        iv, ct, mac = body[:16], body[16:-16], body[-16:]
        want = hmac.new(key, iv + ct, hashlib.blake2b).digest()[:16]
        if not hmac.compare_digest(mac, want):
            raise ValueError("MAC mismatch: wrong key or corrupted data")
        return _xor(ct, _keystream(key, iv, len(ct)))
    raise ValueError(f"unknown seal method {method}")


class MasterKey:
    """Master key backends (master_key/{file,mem}.rs)."""

    def __init__(self, key: bytes):
        if len(key) < 16:
            raise ValueError("master key must be at least 16 bytes")
        self.key = hashlib.blake2b(key, digest_size=32).digest()

    @classmethod
    def from_file(cls, path: str) -> "MasterKey":
        """Hex text (master_key/file.rs format) or raw key bytes.

        The reference's file backend holds exactly one 256-bit key as 64 hex
        chars, so ONLY that shape takes the hex interpretation — an all-hex
        file of any other length is deliberate raw key material (e.g. a
        16-byte binary key that happens to decode as ASCII hex) and must not
        be silently re-encoded into a different key.  A 64-char near-hex
        file is a corrupted hex key, not raw bytes: error loudly."""
        with open(path, "rb") as f:
            raw = f.read()
        try:
            text = raw.decode("ascii")
        except UnicodeDecodeError:
            return cls(raw)  # binary key material
        stripped = text.strip()
        hexish = sum(c in "0123456789abcdefABCDEF" for c in stripped)
        if len(stripped) == 64:
            if hexish == 64:
                return cls(bytes.fromhex(stripped))  # exactly 32 key bytes
            if hexish >= 0.9 * 64:
                # almost-hex at the exact key length: a corrupted hex key
                # file, not deliberate raw bytes
                raise ValueError(f"{path}: looks like hex but fails to parse")
        return cls(raw)

    @classmethod
    def mem(cls, seed: bytes = b"test-master-key-0000") -> "MasterKey":
        return cls(seed)


class DataKeyManager:
    """Rotating data keys sealed under the master key (manager/), with the
    key dictionary persisted to disk (file_dict_file.rs role: atomic
    tmp+rename snapshots of the sealed dict)."""

    def __init__(self, master: MasterKey, dict_path: str | None = None):
        self.master = master
        self._mu = threading.Lock()
        self._persist_mu = threading.Lock()
        self.keys: dict[int, bytes] = {}
        self.current_id = 0
        self.dict_path = dict_path
        self.rotate()

    def rotate(self) -> int:
        """Mint a new data key; new writes use it, old keys stay for reads."""
        with self._mu:
            self.current_id += 1
            self.keys[self.current_id] = os.urandom(32)
            kid = self.current_id
        self._persist()
        return kid

    def rotate_master(self, new_master: MasterKey) -> None:
        """Master-key rotation (master_key/file.rs:10-47 semantics): the data
        keys are unchanged — only the dictionary is re-sealed — so every file
        written under an old data key stays readable without rewriting."""
        with self._mu:
            self.master = new_master
        self._persist()

    def _persist(self) -> None:
        if self.dict_path is None:
            return
        # one persist at a time, export INSIDE the persist lock: two
        # concurrent rotations must not race a stale dict over a newer one
        # (or interleave bytes in the shared tmp file)
        with self._persist_mu:
            blob = self.export_dict()
            tmp = self.dict_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.dict_path)
            # the rename itself must survive a crash (file_dict_file.rs
            # guarantee): fsync the containing directory
            dfd = os.open(os.path.dirname(os.path.abspath(self.dict_path)), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)

    @classmethod
    def open(cls, master: MasterKey, dict_path: str) -> "DataKeyManager":
        """Load the persisted dictionary, or create a fresh manager when the
        path does not exist yet.  A wrong master key fails loudly here — the
        reference likewise refuses to start with an undecryptable dict."""
        if os.path.exists(dict_path):
            with open(dict_path, "rb") as f:
                mgr = cls.import_dict(master, f.read())
            mgr.dict_path = dict_path
            return mgr
        return cls(master, dict_path=dict_path)

    def current(self) -> tuple[int, bytes]:
        with self._mu:
            return self.current_id, self.keys[self.current_id]

    def by_id(self, key_id: int) -> bytes:
        with self._mu:
            k = self.keys.get(key_id)
        if k is None:
            raise ValueError(f"unknown data key {key_id}")
        return k

    def all_keys(self) -> dict[int, bytes]:
        """Snapshot of every data key, for handing the registry to a native
        engine over the FFI (old keys keep old files readable)."""
        with self._mu:
            return dict(self.keys)

    def export_dict(self) -> bytes:
        """The encrypted key dictionary (file_dict_file.rs)."""
        with self._mu:
            out = bytearray()
            out += codec.encode_var_u64(self.current_id)
            out += codec.encode_var_u64(len(self.keys))
            for kid, key in sorted(self.keys.items()):
                out += codec.encode_var_u64(kid)
                out += codec.encode_compact_bytes(key)
        return seal(self.master.key, bytes(out))

    @classmethod
    def import_dict(cls, master: MasterKey, sealed: bytes) -> "DataKeyManager":
        raw = unseal(master.key, sealed)
        mgr = cls.__new__(cls)
        mgr.master = master
        mgr._mu = threading.Lock()
        mgr._persist_mu = threading.Lock()
        mgr.keys = {}
        mgr.dict_path = None
        cur, off = codec.decode_var_u64(raw, 0)
        n, off = codec.decode_var_u64(raw, off)
        for _ in range(n):
            kid, off = codec.decode_var_u64(raw, off)
            key, off = codec.decode_compact_bytes(raw, off)
            mgr.keys[kid] = key
        mgr.current_id = cur
        return mgr


class EncryptedEngine(KvEngine):
    """Engine wrapper encrypting every VALUE at rest (keys stay plaintext for
    ordering, like the reference's file-level encryption leaves RocksDB key
    order intact).  Stored value = varint key_id | sealed(value)."""

    def __init__(self, inner: KvEngine, keys_mgr: DataKeyManager):
        self.inner = inner
        self.keys = keys_mgr

    def _enc(self, value: bytes, cur: tuple[int, bytes] | None = None) -> bytes:
        kid, key = cur if cur is not None else self.keys.current()
        return codec.encode_var_u64(kid) + seal(key, value)

    def _dec(self, stored: bytes) -> bytes:
        kid, off = codec.decode_var_u64(stored, 0)
        return unseal(self.keys.by_id(kid), stored[off:])

    def write(self, batch: WriteBatch) -> None:
        # one key fetch per batch: cheaper, and a batch racing a rotation
        # never straddles two data keys
        cur = self.keys.current()
        enc = WriteBatch()
        for op, cf, key, val in batch.ops:
            if op == "put":
                enc.put_cf(cf, key, self._enc(val, cur))
            elif op == "delete":
                enc.delete_cf(cf, key)
            else:
                enc.delete_range_cf(cf, key, val)
        self.inner.write(enc)

    def snapshot(self) -> "EncryptedSnapshot":
        return EncryptedSnapshot(self.inner.snapshot(), self)

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        v = self.inner.get_cf(cf, key)
        return None if v is None else self._dec(v)

    def scan_cf(self, cf, start, end, limit=None, reverse=False):
        for k, v in self.inner.scan_cf(cf, start, end, limit, reverse):
            yield k, self._dec(v)

    def bulk_load(self, cf: str, items):
        cur = self.keys.current()
        self.inner.bulk_load(cf, [(k, self._enc(v, cur)) for k, v in items])


class _DecCursor(Cursor):
    def __init__(self, inner: Cursor, eng: EncryptedEngine):
        self._c = inner
        self._e = eng

    def seek(self, key):
        return self._c.seek(key)

    def seek_for_prev(self, key):
        return self._c.seek_for_prev(key)

    def seek_to_first(self):
        return self._c.seek_to_first()

    def seek_to_last(self):
        return self._c.seek_to_last()

    def next(self):
        return self._c.next()

    def prev(self):
        return self._c.prev()

    def valid(self):
        return self._c.valid()

    def key(self):
        return self._c.key()

    def value(self):
        return self._e._dec(self._c.value())


class EncryptedSnapshot(Snapshot):
    def __init__(self, inner: Snapshot, eng: EncryptedEngine):
        self._snap = inner
        self._e = eng

    def get_cf(self, cf, key):
        v = self._snap.get_cf(cf, key)
        return None if v is None else self._e._dec(v)

    def cursor_cf(self, cf, lower=None, upper=None):
        return _DecCursor(self._snap.cursor_cf(cf, lower, upper), self._e)
