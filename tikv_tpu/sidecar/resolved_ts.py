"""Resolved-ts tracking: the stale-read / CDC watermark.

Re-expression of ``components/resolved_ts`` (resolver.rs:14 ``Resolver``:
locks_by_key + ts heap; endpoint.rs advance loop): every applied prewrite
registers its lock, every commit/rollback untracks it, and
``resolved_ts = max(resolved, min(pending lock ts) - 1 or advance ts)``:
reads at or below the watermark never block, which is what enables follower
stale reads and CDC's consistency guarantee.
"""

from __future__ import annotations

import heapq

from ..analysis.sanitizer import make_lock


class Resolver:
    """Per-region lock tracker (resolver.rs)."""

    def __init__(self, region_id: int):
        self.region_id = region_id
        self._mu = make_lock("resolved_ts.resolver")
        self.locks_by_key: dict[bytes, int] = {}
        self._ts_heap: list[tuple[int, bytes]] = []
        self.resolved_ts = 0

    def track_lock(self, start_ts: int, key: bytes) -> None:
        with self._mu:
            self.locks_by_key[key] = start_ts
            heapq.heappush(self._ts_heap, (start_ts, key))

    def untrack_lock(self, key: bytes) -> None:
        with self._mu:
            self.locks_by_key.pop(key, None)

    def resolve(self, advance_to: int) -> int:
        """Advance the watermark toward ``advance_to`` (a fresh TSO)."""
        with self._mu:
            # drop stale heap heads (already untracked or re-locked newer)
            while self._ts_heap:
                ts, key = self._ts_heap[0]
                if self.locks_by_key.get(key) != ts:
                    heapq.heappop(self._ts_heap)
                    continue
                break
            if self._ts_heap:
                min_lock_ts = self._ts_heap[0][0]
                candidate = min(advance_to, min_lock_ts - 1)
            else:
                candidate = advance_to
            self.resolved_ts = max(self.resolved_ts, candidate)
            return self.resolved_ts


class ResolvedTsEndpoint:
    """Store-level advance loop over region resolvers (endpoint.rs:247 +
    advance.rs): observes applied commands, periodically advances every
    resolver with a fresh TSO from PD.

    Watermarks are published as RegionReadProgress pairs —
    (resolved_ts, required_apply_index) computed on the LEADER — and a
    follower may serve a stale read only once its own applied index reaches
    the paired index (store/util.rs RegionReadProgress)."""

    def __init__(self, pd, store_id: int | None = None, check_leader_send=None,
                 feature_gate=None):
        self.pd = pd
        self._mu = make_lock("resolved_ts.endpoint")
        self.resolvers: dict[int, Resolver] = {}
        self.stores: list = []
        # region_id -> (resolved_ts, required_apply_index)
        self.read_progress: dict[int, tuple[int, int]] = {}
        # cross-process mode (advance.rs:75,211): this store's id plus a
        # sender ``(store_id, payload) -> response dict | None`` that carries
        # one check_leader RPC to a peer store.  The same RPC confirms
        # leadership (a quorum of matching (term, leader) views) AND
        # disseminates the previous round's confirmed watermarks, so
        # follower stale reads advance without leases and without waking
        # hibernated groups.
        self.store_id = store_id
        self._check_leader_send = check_leader_send
        # version gate (feature_gate.rs): the RPC fan-out stays off until
        # every store in the cluster can answer raft_check_leader
        self.feature_gate = feature_gate
        self._pending_progress: dict[int, tuple[int, int]] = {}

    def attach_store(self, store) -> None:
        store.apply_observers.append(self.observe_apply)
        self.stores.append(store)

    def resolver(self, region_id: int) -> Resolver:
        with self._mu:
            r = self.resolvers.get(region_id)
            if r is None:
                r = Resolver(region_id)
                self.resolvers[region_id] = r
            return r

    def observe_apply(self, store, region, cmd: dict) -> None:
        """raftstore apply observer: track/untrack locks from data commands."""
        from ..storage.engine import CF_LOCK

        r = self.resolver(region.id)
        for op, cf, key, val in cmd.get("ops", ()):
            if cf != CF_LOCK:
                continue
            if op == "put":
                from ..storage.txn_types import Lock

                try:
                    lock = Lock.from_bytes(val)
                except ValueError:
                    continue
                r.track_lock(lock.ts, key)
            elif op == "delete":
                r.untrack_lock(key)

    def _leader_confirmed(self, rid: int, peer) -> bool:
        """CheckLeader-equivalent leadership confirmation (advance.rs).

        A valid lease is already a quorum ack within an election timeout.
        Without one (e.g. the group hibernated, which freezes the tick
        clock and zeroes the lease), fall back to counting peers that
        recognize this leader at its current term — a quorum of matching
        (term, leader_id) views is exactly what CheckLeader RPCs collect,
        and it lets hibernated regions keep advancing without being woken.
        """
        node = peer.node
        if node.lease_valid():
            return True
        if not node.is_leader():
            return False
        votes = {node.id}
        visible = {node.id}
        for store in self.stores:
            p = store.peers.get(rid)
            if p is None or p.node is node:
                continue
            visible.add(p.node.id)
            if p.node.term == node.term and p.node.leader_id == node.id:
                votes.add(p.node.id)
        if not node._has_quorum(visible):
            # This endpoint cannot see a voter majority (per-store
            # deployment): it cannot run the CheckLeader count locally, so
            # a hibernated leader would freeze the watermark forever.  Wake
            # the group — the next heartbeat round re-grants the lease and
            # a later advance pass publishes under it.
            if node.hibernated:
                node._wake()
            return False
        return node._has_quorum(votes)

    def advance_all(self) -> dict[int, int]:
        """Advance watermarks from leader peers, pairing each with the
        leader's applied index at resolution time.  Leadership is confirmed
        by lease, by the in-process peer census (single-process clusters),
        or by a check_leader RPC quorum across stores (the deployment
        shape)."""
        ts = self.pd.get_tso()
        out: dict[int, int] = {}
        with self._mu:
            resolvers = list(self.resolvers.values())
        rpc_on = self._check_leader_send is not None and (
            self.feature_gate is None or self._gate_ok()
        )
        leader_peers: dict[int, object] = {}
        rpc_candidates: dict[int, object] = {}
        rpc_leaders: dict[int, object] = {}
        for store in self.stores:
            for rid, peer in list(store.peers.items()):
                # Quorum-confirmed leadership, not bare is_leader(): a
                # deposed leader that hasn't heard the new term must never
                # publish a watermark past locks it never applied
                # (resolved_ts advance.rs confirms via CheckLeader RPCs).
                if peer.node.lease_valid() and peer.node.is_leader():
                    leader_peers[rid] = peer
                    rpc_leaders[rid] = peer
                elif rpc_on:
                    if peer.node.is_leader():
                        rpc_candidates[rid] = peer
                        rpc_leaders[rid] = peer
                elif self._leader_confirmed(rid, peer):
                    leader_peers[rid] = peer
        confirmed_rpc: set[int] = set()
        if rpc_on and rpc_leaders:
            # ONE fan-out per round even when every lease is valid: the RPC
            # is what carries the previous round's confirmed watermarks to
            # follower stores — without it their RegionReadProgress never
            # moves and follower stale reads never serve
            confirmed_rpc = self._check_leader_round(rpc_candidates, rpc_leaders)
        for rid in confirmed_rpc:
            leader_peers[rid] = rpc_candidates[rid]
        progress_batch: dict[int, tuple[int, int]] = {}
        for r in resolvers:
            resolved = r.resolve(ts)
            out[r.region_id] = resolved
            leader = leader_peers.get(r.region_id)
            if leader is not None:
                pair = (resolved, leader.apply_index)
                with self._mu:
                    self.read_progress[r.region_id] = pair
                progress_batch[r.region_id] = pair
        with self._mu:
            # confirmed pairs ride the NEXT round's check_leader RPCs out to
            # follower stores (their RegionReadProgress update)
            self._pending_progress = dict(progress_batch)
        # staleness-risk gauge: how far the store's stale-read floor trails
        # the TSO this round advanced toward.  Operators see the lag grow
        # when a leader is unreachable or dissemination stalls BEFORE stale
        # reads start refusing (docs/stale_reads.md)
        self._gauge_safe_ts_lag(ts)
        return out

    def _gauge_safe_ts_lag(self, now_ts: int) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.gauge(
            "tikv_resolved_ts_safe_ts_lag",
            "Store safe_ts lag behind the latest TSO (timestamp units): "
            "staleness risk for follower reads on this store",
        ).set(max(now_ts - self.safe_ts(), 0))

    def _gate_ok(self) -> bool:
        from ..pd.feature_gate import RESOLVED_TS_CHECK_LEADER

        return self.feature_gate.can_enable(RESOLVED_TS_CHECK_LEADER)

    def _check_leader_round(self, candidates: dict[int, object],
                            all_leaders: dict[int, object]) -> set[int]:
        """check_leader fan-out (advance.rs:211): one RPC per peer store,
        sent CONCURRENTLY (a dead peer costs one timeout, not one per
        store), carrying (a) every lease-less candidate region's (term,
        leader) claim for quorum confirmation and (b) the last round's
        confirmed watermarks for every led region — the follower
        RegionReadProgress update.  Hibernated groups on either side answer
        from their frozen term — nobody wakes."""
        by_store: dict[int, list] = {}
        votes: dict[int, set] = {}
        voters: dict[int, set] = {}
        peer_stores: set[int] = set()
        for rid, peer in all_leaders.items():
            for p in peer.region.peers:
                if p.store_id != self.store_id:
                    peer_stores.add(p.store_id)
        for rid, peer in candidates.items():
            node = peer.node
            votes[rid] = {self.store_id}
            voters[rid] = set()
            for p in peer.region.peers:
                if p.role == "learner":
                    continue  # learners don't vote; witnesses do
                voters[rid].add(p.store_id)
                if p.store_id != self.store_id:
                    by_store.setdefault(p.store_id, []).append(
                        {"region_id": rid, "term": node.term, "leader_id": node.id}
                    )
        with self._mu:
            pending = dict(self._pending_progress)
        if not peer_stores:
            # no peer stores to ask (single-replica regions, or every other
            # replica lives on this store): the self-vote alone must still be
            # tallied against each region's voter set, or single-replica
            # regions never confirm and read_progress stalls in RPC mode
            confirmed: set[int] = set()
            for rid in candidates:
                n_voters = max(len(voters[rid]), 1)
                if len(votes[rid]) * 2 > n_voters:
                    confirmed.add(rid)
            return confirmed

        def one(sid):
            payload = {
                "regions": by_store.get(sid, []),
                "progress": {str(rid): list(pair) for rid, pair in pending.items()},
            }
            try:
                return sid, self._check_leader_send(sid, payload)
            except Exception:  # noqa: BLE001 — peer store down: no vote
                return sid, None

        import concurrent.futures as _fut

        with _fut.ThreadPoolExecutor(max_workers=min(len(peer_stores), 8)) as pool:
            results = list(pool.map(one, sorted(peer_stores)))
        for sid, resp in results:
            if not isinstance(resp, dict):
                continue
            for rid in resp.get("accepted", ()):
                if rid in votes:
                    votes[rid].add(sid)
        confirmed: set[int] = set()
        for rid in candidates:
            n_voters = max(len(voters[rid]), 1)
            if len(votes[rid]) * 2 > n_voters:
                confirmed.add(rid)
        return confirmed

    def handle_check_leader(self, req: dict) -> dict:
        """Peer-store side of the fan-out: acknowledge regions whose local
        raft state matches the claimed (term, leader) — readable WITHOUT
        waking a hibernated group — and adopt the disseminated watermarks
        (the follower RegionReadProgress update that makes stale reads on
        this store advance while the leader lives elsewhere)."""
        accepted: list[int] = []
        store = self.stores[0] if self.stores else None
        if store is None:
            return {"accepted": []}
        for entry in req.get("regions", ()):
            rid = entry.get("region_id")
            p = store.peers.get(rid)
            if p is None:
                continue
            node = p.node
            if node.term == entry.get("term") and node.leader_id == entry.get("leader_id"):
                accepted.append(rid)
        for rid_s, pair in (req.get("progress") or {}).items():
            rid = int(rid_s)
            p = store.peers.get(rid)
            if p is None or len(pair) != 2:
                continue
            rts, ridx = int(pair[0]), int(pair[1])
            with self._mu:
                cur = self.read_progress.get(rid, (0, 0))
                if rts > cur[0]:
                    self.read_progress[rid] = (rts, ridx)
        return {"accepted": accepted}

    def progress_of(self, region_id: int) -> tuple[int, int]:
        with self._mu:
            return self.read_progress.get(region_id, (0, 0))

    def progress_snapshot(self) -> dict[int, tuple[int, int]]:
        """Every known region's RegionReadProgress pair — disseminated pairs
        first, local resolver watermarks (required index 0) for regions with
        no pair yet.  The stuck-follower debugging surface behind
        ``ctl.py read-progress`` and ``/debug/read_progress``."""
        with self._mu:
            out = dict(self.read_progress)
            for rid, r in self.resolvers.items():
                out.setdefault(rid, (r.resolved_ts, 0))
        return out

    def safe_ts(self) -> int:
        """Store-level stale-read floor (kv.rs:1034 get_store_safe_ts): the
        minimum RegionReadProgress watermark across regions hosted on the
        attached stores — on a follower store that is the DISSEMINATED
        pair, which local resolvers never advance.  A hosted region with no
        pair yet falls back to its local resolver watermark (the leader
        store between advance rounds); 0 with no hosted regions."""
        with self._mu:
            progress = dict(self.read_progress)
            resolvers = {rid: r.resolved_ts for rid, r in self.resolvers.items()}
        rids: set[int] = set()
        for store in self.stores:
            rids.update(list(store.peers))
        if not rids:
            # detached endpoint (tests, embedded): every tracked region counts
            rids = set(progress) | set(resolvers)
        if not rids:
            return 0
        return min(
            progress.get(rid, (resolvers.get(rid, 0), 0))[0] for rid in rids
        )

    def min_resolved_ts(self) -> int:
        with self._mu:
            if not self.resolvers:
                return 0
            return min(r.resolved_ts for r in self.resolvers.values())
