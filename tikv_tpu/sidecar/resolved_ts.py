"""Resolved-ts tracking: the stale-read / CDC watermark.

Re-expression of ``components/resolved_ts`` (resolver.rs:14 ``Resolver``:
locks_by_key + ts heap; endpoint.rs advance loop): every applied prewrite
registers its lock, every commit/rollback untracks it, and
``resolved_ts = max(resolved, min(pending lock ts) - 1 or advance ts)``:
reads at or below the watermark never block, which is what enables follower
stale reads and CDC's consistency guarantee.
"""

from __future__ import annotations

import heapq
import threading


class Resolver:
    """Per-region lock tracker (resolver.rs)."""

    def __init__(self, region_id: int):
        self.region_id = region_id
        self._mu = threading.Lock()
        self.locks_by_key: dict[bytes, int] = {}
        self._ts_heap: list[tuple[int, bytes]] = []
        self.resolved_ts = 0

    def track_lock(self, start_ts: int, key: bytes) -> None:
        with self._mu:
            self.locks_by_key[key] = start_ts
            heapq.heappush(self._ts_heap, (start_ts, key))

    def untrack_lock(self, key: bytes) -> None:
        with self._mu:
            self.locks_by_key.pop(key, None)

    def resolve(self, advance_to: int) -> int:
        """Advance the watermark toward ``advance_to`` (a fresh TSO)."""
        with self._mu:
            # drop stale heap heads (already untracked or re-locked newer)
            while self._ts_heap:
                ts, key = self._ts_heap[0]
                if self.locks_by_key.get(key) != ts:
                    heapq.heappop(self._ts_heap)
                    continue
                break
            if self._ts_heap:
                min_lock_ts = self._ts_heap[0][0]
                candidate = min(advance_to, min_lock_ts - 1)
            else:
                candidate = advance_to
            self.resolved_ts = max(self.resolved_ts, candidate)
            return self.resolved_ts


class ResolvedTsEndpoint:
    """Store-level advance loop over region resolvers (endpoint.rs:247 +
    advance.rs): observes applied commands, periodically advances every
    resolver with a fresh TSO from PD.

    Watermarks are published as RegionReadProgress pairs —
    (resolved_ts, required_apply_index) computed on the LEADER — and a
    follower may serve a stale read only once its own applied index reaches
    the paired index (store/util.rs RegionReadProgress)."""

    def __init__(self, pd):
        self.pd = pd
        self._mu = threading.Lock()
        self.resolvers: dict[int, Resolver] = {}
        self.stores: list = []
        # region_id -> (resolved_ts, required_apply_index)
        self.read_progress: dict[int, tuple[int, int]] = {}

    def attach_store(self, store) -> None:
        store.apply_observers.append(self.observe_apply)
        self.stores.append(store)

    def resolver(self, region_id: int) -> Resolver:
        with self._mu:
            r = self.resolvers.get(region_id)
            if r is None:
                r = Resolver(region_id)
                self.resolvers[region_id] = r
            return r

    def observe_apply(self, store, region, cmd: dict) -> None:
        """raftstore apply observer: track/untrack locks from data commands."""
        from ..storage.engine import CF_LOCK

        r = self.resolver(region.id)
        for op, cf, key, val in cmd.get("ops", ()):
            if cf != CF_LOCK:
                continue
            if op == "put":
                from ..storage.txn_types import Lock

                try:
                    lock = Lock.from_bytes(val)
                except ValueError:
                    continue
                r.track_lock(lock.ts, key)
            elif op == "delete":
                r.untrack_lock(key)

    def _leader_confirmed(self, rid: int, peer) -> bool:
        """CheckLeader-equivalent leadership confirmation (advance.rs).

        A valid lease is already a quorum ack within an election timeout.
        Without one (e.g. the group hibernated, which freezes the tick
        clock and zeroes the lease), fall back to counting peers that
        recognize this leader at its current term — a quorum of matching
        (term, leader_id) views is exactly what CheckLeader RPCs collect,
        and it lets hibernated regions keep advancing without being woken.
        """
        node = peer.node
        if node.lease_valid():
            return True
        if not node.is_leader():
            return False
        votes = {node.id}
        visible = {node.id}
        for store in self.stores:
            p = store.peers.get(rid)
            if p is None or p.node is node:
                continue
            visible.add(p.node.id)
            if p.node.term == node.term and p.node.leader_id == node.id:
                votes.add(p.node.id)
        if not node._has_quorum(visible):
            # This endpoint cannot see a voter majority (per-store
            # deployment): it cannot run the CheckLeader count locally, so
            # a hibernated leader would freeze the watermark forever.  Wake
            # the group — the next heartbeat round re-grants the lease and
            # a later advance pass publishes under it.
            if node.hibernated:
                node._wake()
            return False
        return node._has_quorum(votes)

    def advance_all(self) -> dict[int, int]:
        """Advance watermarks from leader peers, pairing each with the
        leader's applied index at resolution time."""
        ts = self.pd.get_tso()
        out: dict[int, int] = {}
        with self._mu:
            resolvers = list(self.resolvers.values())
        leader_peers: dict[int, object] = {}
        for store in self.stores:
            for rid, peer in list(store.peers.items()):
                # Quorum-confirmed leadership, not bare is_leader(): a
                # deposed leader that hasn't heard the new term must never
                # publish a watermark past locks it never applied
                # (resolved_ts advance.rs confirms via CheckLeader RPCs).
                if self._leader_confirmed(rid, peer):
                    leader_peers[rid] = peer
        for r in resolvers:
            resolved = r.resolve(ts)
            out[r.region_id] = resolved
            leader = leader_peers.get(r.region_id)
            if leader is not None:
                with self._mu:
                    self.read_progress[r.region_id] = (resolved, leader.node.applied)
        return out

    def progress_of(self, region_id: int) -> tuple[int, int]:
        with self._mu:
            return self.read_progress.get(region_id, (0, 0))

    def min_resolved_ts(self) -> int:
        with self._mu:
            if not self.resolvers:
                return 0
            return min(r.resolved_ts for r in self.resolvers.values())
