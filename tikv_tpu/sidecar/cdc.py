"""Change data capture: stream committed row changes per region.

Re-expression of ``components/cdc`` (observer.rs CmdObserver; delegate.rs
per-region Delegate; endpoint.rs; old_value.rs): an apply observer watches
the raft apply stream, pairs prewrites with their commits, and emits ordered
row-change events (with old value) to downstream sinks; a new subscription
first runs an incremental scan of existing data at its start ts, then streams
live events gated by the resolver's resolved-ts watermark.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE
from ..storage.txn_types import Key, Lock, LockType, Write, WriteType, split_ts


@dataclass
class ChangeEvent:
    region_id: int
    key: bytes  # raw user key
    op: str  # "put" | "delete"
    value: bytes | None
    old_value: bytes | None
    start_ts: int
    commit_ts: int


class Sink:
    """Downstream consumer (channel.rs's memory-quota sink, simplified)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.events: list[ChangeEvent] = []
        self.resolved: list[tuple[int, int]] = []  # (region_id, resolved_ts)

    def emit(self, event: ChangeEvent) -> None:
        with self._mu:
            self.events.append(event)

    def emit_resolved(self, region_id: int, ts: int) -> None:
        with self._mu:
            self.resolved.append((region_id, ts))


class CdcDelegate:
    """Per-region capture state (delegate.rs:192): pending prewrites keyed by
    (key, start_ts) until their commit arrives."""

    def __init__(self, region_id: int, sink: Sink):
        self.region_id = region_id
        self.sink = sink
        self.pending: dict[tuple[bytes, int], tuple[str, bytes | None, bytes | None]] = {}

    def on_prewrite(self, key: bytes, lock: Lock, old_value: bytes | None) -> None:
        op = "delete" if lock.lock_type == LockType.DELETE else "put"
        self.pending[(key, lock.ts)] = (op, lock.short_value, old_value)

    def on_commit(self, key: bytes, write: Write, commit_ts: int) -> None:
        ent = self.pending.pop((key, write.start_ts), None)
        if write.write_type in (WriteType.ROLLBACK, WriteType.LOCK):
            # LOCK records come from lock-only/pessimistic commits — no data
            # change, so no event (delegate.rs filters them the same way)
            return
        if ent is None:
            # commit without observed prewrite (e.g. subscribed mid-txn)
            op = "delete" if write.write_type == WriteType.DELETE else "put"
            value, old = write.short_value, None
        else:
            op, value, old = ent
            if write.write_type == WriteType.DELETE:
                op = "delete"
        self.sink.emit(
            ChangeEvent(self.region_id, key, op, value, old, write.start_ts, commit_ts)
        )


class CdcObserver:
    """The raftstore apply observer wiring (observer.rs:26)."""

    def __init__(self, sink: Sink | None = None):
        self.sink = sink or Sink()
        self._mu = threading.Lock()
        self.delegates: dict[int, CdcDelegate] = {}
        self.subscribed: set[int] = set()

    def subscribe(self, region_id: int) -> CdcDelegate:
        with self._mu:
            self.subscribed.add(region_id)
            d = self.delegates.get(region_id)
            if d is None:
                d = CdcDelegate(region_id, self.sink)
                self.delegates[region_id] = d
            return d

    def unsubscribe(self, region_id: int) -> None:
        with self._mu:
            self.subscribed.discard(region_id)
            self.delegates.pop(region_id, None)

    def incremental_scan(self, snapshot, region_id: int, start_ts: int) -> int:
        """Emit existing committed data up to ``start_ts`` (scanner.rs)."""
        from ..storage.mvcc import ForwardScanner

        d = self.subscribe(region_id)
        n = 0
        for raw_key, value in ForwardScanner(snapshot, start_ts, None, None):
            self.sink.emit(
                ChangeEvent(region_id, raw_key, "put", value, None, 0, start_ts)
            )
            n += 1
        return n

    # -- raftstore observer hook -------------------------------------------

    def observe_apply(self, store, region, cmd: dict) -> None:
        with self._mu:
            d = self.delegates.get(region.id)
        if d is None or region.id not in self.subscribed:
            return
        # capture on the leader only — every replica applies the command, but
        # a subscription is served by the region leader (endpoint.rs keeps
        # delegates on leaders and unsubscribes on role change)
        peer = store.peers.get(region.id)
        if peer is None or not peer.node.is_leader():
            return
        snapshot = store.engine.snapshot()
        from ..util import keys as keymod

        ops = cmd.get("ops", ())
        # long values ride in CF_DEFAULT within the same command; index them
        # by encoded key+start_ts so prewrite events carry the real value
        defaults = {key: val for op, cf, key, val in ops if cf == CF_DEFAULT and op == "put"}
        from ..storage.txn_types import append_ts

        for op, cf, key, val in ops:
            if cf == CF_LOCK and op == "put":
                try:
                    lock = Lock.from_bytes(val)
                except ValueError:
                    continue
                if lock.lock_type in (LockType.PUT, LockType.DELETE):
                    raw = Key.from_encoded(key).to_raw()
                    old = _read_old_value(snapshot, keymod, key, lock.ts)
                    if lock.short_value is None and lock.lock_type == LockType.PUT:
                        lock.short_value = defaults.get(append_ts(key, lock.ts))
                    d.on_prewrite(raw, lock, old)
            elif cf == CF_WRITE and op == "put":
                user_enc, commit_ts = split_ts(key)
                try:
                    write = Write.from_bytes(val)
                except ValueError:
                    continue
                raw = Key.from_encoded(user_enc).to_raw()
                d.on_commit(raw, write, commit_ts)

    def emit_resolved(self, region_id: int, ts: int) -> None:
        self.sink.emit_resolved(region_id, ts)


def _read_old_value(snapshot, keymod, enc_key: bytes, before_ts: int) -> bytes | None:
    """old_value.rs: the committed value the prewrite overwrites."""
    from ..storage.mvcc import PointGetter
    from ..storage.mvcc.reader import IsolationLevel

    try:
        return PointGetter(
            _DataView(snapshot, keymod), before_ts - 1, isolation=IsolationLevel.RC
        ).get(Key.from_encoded(enc_key))
    except Exception:  # noqa: BLE001 — old value is best-effort
        return None


class _DataView:
    """Engine snapshot with the z-prefix applied (observer reads applied state)."""

    def __init__(self, snap, keymod):
        self._snap = snap
        self._k = keymod

    def get_cf(self, cf, key):
        return self._snap.get_cf(cf, self._k.data_key(key))

    def cursor_cf(self, cf, lower=None, upper=None):
        from ..raft.raftkv import _PrefixCursor

        lo = self._k.data_key(lower) if lower is not None else self._k.DATA_MIN_KEY
        hi = self._k.data_key(upper) if upper is not None else self._k.DATA_MAX_KEY
        return _PrefixCursor(self._snap.cursor_cf(cf, lo, hi))

    def scan_cf(self, cf, start, end, limit=None, reverse=False):
        from ..storage.engine import Snapshot

        return Snapshot.scan_cf(self, cf, start, end, limit, reverse)


# ---------------------------------------------------------------------------
# Wire service (cdcpb ChangeData: service.rs register_region/EventFeed)
# ---------------------------------------------------------------------------


class SeqSink(Sink):
    """Sink with per-event sequence numbers so wire clients pull-resume
    (the push EventFeed stream adapted to the request/response transport:
    register → pull events after a seq → deregister)."""

    def __init__(self):
        super().__init__()
        self._seq = 0
        self._cv = threading.Condition(self._mu)
        self.items: list[tuple[int, str, object]] = []  # (seq, kind, payload)

    def emit(self, event: ChangeEvent) -> None:
        with self._cv:
            self._seq += 1
            self.items.append((self._seq, "event", event))
            self._cv.notify_all()

    def emit_resolved(self, region_id: int, ts: int) -> None:
        with self._cv:
            self._seq += 1
            self.items.append((self._seq, "resolved", (region_id, ts)))
            self._cv.notify_all()

    def drain_after(
        self, after_seq: int, limit: int, timeout: float = 0.0
    ) -> list[tuple[int, str, object]]:
        with self._cv:
            # drop everything at or below the client's ack: memory stays
            # bounded by the client's pull cadence
            while self.items and self.items[0][0] <= after_seq:
                self.items.pop(0)
            if not self.items and timeout > 0:
                # long-poll: the push EventFeed's latency without its stream
                self._cv.wait(timeout)
                while self.items and self.items[0][0] <= after_seq:
                    self.items.pop(0)
            return list(self.items[:limit])


class CdcService:
    """The ChangeData service surface: one observer shared by the store's
    apply pipeline, per-subscription SeqSinks, pull-based event feed."""

    def __init__(self, store, snapshot_fn=None):
        from ..util import keys as keymod

        self.store = store
        # the store engine speaks the z-prefixed data keyspace; scans must see
        # user keys, exactly like the observer's old-value reads
        self._snapshot_fn = snapshot_fn or (
            lambda: _DataView(store.engine.snapshot(), keymod)
        )
        self._mu = threading.Lock()
        self._subs: dict[int, tuple[int, CdcObserver]] = {}  # sub_id -> (region, obs)
        self._next_id = 0
        store.apply_observers.append(self._observe)

    def _observe(self, store, region, cmd):
        with self._mu:
            observers = [obs for _rid, obs in self._subs.values()]
        for obs in observers:
            obs.observe_apply(store, region, cmd)

    def register(self, region_id: int, checkpoint_ts: int) -> dict:
        """register_region: subscribe + incremental scan from the checkpoint
        (delta changes after checkpoint_ts stream via the observer)."""
        peer = self.store.peers.get(region_id)
        if peer is None:
            return {"error": {"other": f"region {region_id} not on this store"}}
        if not peer.node.is_leader():
            return {"error": {"not_leader": region_id}}
        obs = CdcObserver(sink=SeqSink())
        # install the delegate BEFORE taking the scan snapshot (the reference
        # does the same): an apply landing in between shows up as a delta
        # event — possibly duplicating a scan row, which is the documented
        # at-least-once overlap — instead of being silently lost
        with self._mu:
            self._next_id += 1
            sub_id = self._next_id
            self._subs[sub_id] = (region_id, obs)
        scanned = obs.incremental_scan(self._snapshot_fn(), region_id, checkpoint_ts)
        return {"sub_id": sub_id, "scanned": scanned}

    def events(
        self, sub_id: int, after_seq: int = 0, limit: int = 1024, timeout: float = 0.0
    ) -> dict:
        with self._mu:
            ent = self._subs.get(sub_id)
        if ent is None:
            return {"error": {"other": f"unknown cdc subscription {sub_id}"}}
        region_id, obs = ent
        peer = self.store.peers.get(region_id)
        if peer is None or not peer.node.is_leader():
            # role changed: the reference tears the delegate down and the
            # client re-registers against the new leader
            self.deregister(sub_id)
            return {"error": {"not_leader": region_id}}
        out = []
        last = after_seq
        for seq, kind, payload in obs.sink.drain_after(after_seq, limit, timeout):
            last = seq
            if kind == "event":
                e: ChangeEvent = payload
                out.append({
                    "seq": seq, "type": e.op, "key": e.key,
                    "value": e.value if e.value is not None else b"",
                    "old_value": e.old_value if e.old_value is not None else b"",
                    "start_ts": e.start_ts, "commit_ts": e.commit_ts,
                })
            else:
                rid, ts = payload
                out.append({"seq": seq, "type": "resolved", "region_id": rid, "ts": ts})
        return {"events": out, "last_seq": last}

    def resolved(self, sub_id: int, ts: int) -> dict:
        """Advance the subscription's resolved-ts watermark (the resolved-ts
        worker calls this; clients see it interleaved in the event feed)."""
        with self._mu:
            ent = self._subs.get(sub_id)
        if ent is None:
            return {"error": {"other": f"unknown cdc subscription {sub_id}"}}
        region_id, obs = ent
        obs.emit_resolved(region_id, ts)
        return {}

    def deregister(self, sub_id: int) -> dict:
        with self._mu:
            ent = self._subs.pop(sub_id, None)
        if ent is not None:
            ent[1].unsubscribe(ent[0])
        return {}
