"""Change data capture: stream committed row changes per region.

Re-expression of ``components/cdc`` (observer.rs CmdObserver; delegate.rs
per-region Delegate; endpoint.rs; old_value.rs): an apply observer watches
the raft apply stream, pairs prewrites with their commits, and emits ordered
row-change events (with old value) to downstream sinks; a new subscription
first runs an incremental scan of existing data at its start ts, then streams
live events gated by the resolver's resolved-ts watermark.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE
from ..storage.txn_types import Key, Lock, LockType, Write, WriteType, split_ts


@dataclass
class ChangeEvent:
    region_id: int
    key: bytes  # raw user key
    op: str  # "put" | "delete"
    value: bytes | None
    old_value: bytes | None
    start_ts: int
    commit_ts: int


def _event_bytes(event: ChangeEvent) -> int:
    """Approximate resident size of one buffered event (channel.rs
    CdcEvent::size role): payload bytes + fixed object overhead."""
    n = 96 + len(event.key)
    if event.value is not None:
        n += len(event.value)
    if event.old_value is not None:
        n += len(event.old_value)
    return n


class Sink:
    """Downstream consumer (channel.rs's memory-quota sink, simplified)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.events: list[ChangeEvent] = []
        self.resolved: list[tuple[int, int]] = []  # (region_id, resolved_ts)

    def emit(self, event: ChangeEvent) -> None:
        with self._mu:
            self.events.append(event)

    def emit_scan(self, event: ChangeEvent) -> bool:
        """Incremental-scan emission; quota-bounded sinks override this to
        PAUSE the scanner when full.  True = accepted, keep scanning."""
        self.emit(event)
        return True

    def emit_resolved(self, region_id: int, ts: int) -> None:
        with self._mu:
            self.resolved.append((region_id, ts))


class CdcDelegate:
    """Per-region capture state (delegate.rs:192): pending prewrites keyed by
    (key, start_ts) until their commit arrives."""

    def __init__(self, region_id: int, sink: Sink):
        self.region_id = region_id
        self.sink = sink
        self.pending: dict[tuple[bytes, int], tuple[str, bytes | None, bytes | None]] = {}

    def on_prewrite(self, key: bytes, lock: Lock, old_value: bytes | None) -> None:
        op = "delete" if lock.lock_type == LockType.DELETE else "put"
        self.pending[(key, lock.ts)] = (op, lock.short_value, old_value)

    def on_commit(self, key: bytes, write: Write, commit_ts: int) -> None:
        ent = self.pending.pop((key, write.start_ts), None)
        if write.write_type in (WriteType.ROLLBACK, WriteType.LOCK):
            # LOCK records come from lock-only/pessimistic commits — no data
            # change, so no event (delegate.rs filters them the same way)
            return
        if ent is None:
            # commit without observed prewrite (e.g. subscribed mid-txn)
            op = "delete" if write.write_type == WriteType.DELETE else "put"
            value, old = write.short_value, None
        else:
            op, value, old = ent
            if write.write_type == WriteType.DELETE:
                op = "delete"
        self.sink.emit(
            ChangeEvent(self.region_id, key, op, value, old, write.start_ts, commit_ts)
        )


class CdcObserver:
    """The raftstore apply observer wiring (observer.rs:26)."""

    def __init__(self, sink: Sink | None = None):
        self.sink = sink or Sink()
        self._mu = threading.Lock()
        self.delegates: dict[int, CdcDelegate] = {}
        self.subscribed: set[int] = set()

    def subscribe(self, region_id: int) -> CdcDelegate:
        with self._mu:
            self.subscribed.add(region_id)
            d = self.delegates.get(region_id)
            if d is None:
                d = CdcDelegate(region_id, self.sink)
                self.delegates[region_id] = d
            return d

    def unsubscribe(self, region_id: int) -> None:
        with self._mu:
            self.subscribed.discard(region_id)
            self.delegates.pop(region_id, None)

    def incremental_scan(self, snapshot, region_id: int, start_ts: int) -> int:
        """Emit existing committed data up to ``start_ts`` (scanner.rs).
        Quota-bounded sinks PAUSE the scan while the buffer is full — the
        client's drains release quota and the scan resumes; a sink that
        stays full past its patience aborts the scan (congested)."""
        from ..storage.mvcc import ForwardScanner

        d = self.subscribe(region_id)
        n = 0
        for raw_key, value in ForwardScanner(snapshot, start_ts, None, None):
            if not self.sink.emit_scan(
                ChangeEvent(region_id, raw_key, "put", value, None, 0, start_ts)
            ):
                break  # congested beyond patience: subscription is torn down
            n += 1
        return n

    # -- raftstore observer hook -------------------------------------------

    def observe_apply(self, store, region, cmd: dict) -> None:
        with self._mu:
            d = self.delegates.get(region.id)
        if d is None or region.id not in self.subscribed:
            return
        # capture on the leader only — every replica applies the command, but
        # a subscription is served by the region leader (endpoint.rs keeps
        # delegates on leaders and unsubscribes on role change)
        peer = store.peers.get(region.id)
        if peer is None or not peer.node.is_leader():
            return
        snapshot = store.engine.snapshot()
        from ..util import keys as keymod

        ops = cmd.get("ops", ())
        # long values ride in CF_DEFAULT within the same command; index them
        # by encoded key+start_ts so prewrite events carry the real value
        defaults = {key: val for op, cf, key, val in ops if cf == CF_DEFAULT and op == "put"}
        from ..storage.txn_types import append_ts

        for op, cf, key, val in ops:
            if cf == CF_LOCK and op == "put":
                try:
                    lock = Lock.from_bytes(val)
                except ValueError:
                    continue
                if lock.lock_type in (LockType.PUT, LockType.DELETE):
                    raw = Key.from_encoded(key).to_raw()
                    old = _read_old_value(snapshot, keymod, key, lock.ts)
                    if lock.short_value is None and lock.lock_type == LockType.PUT:
                        lock.short_value = defaults.get(append_ts(key, lock.ts))
                    d.on_prewrite(raw, lock, old)
            elif cf == CF_WRITE and op == "put":
                user_enc, commit_ts = split_ts(key)
                try:
                    write = Write.from_bytes(val)
                except ValueError:
                    continue
                raw = Key.from_encoded(user_enc).to_raw()
                d.on_commit(raw, write, commit_ts)

    def emit_resolved(self, region_id: int, ts: int) -> None:
        self.sink.emit_resolved(region_id, ts)


def _read_old_value(snapshot, keymod, enc_key: bytes, before_ts: int) -> bytes | None:
    """old_value.rs: the committed value the prewrite overwrites."""
    from ..storage.mvcc import PointGetter
    from ..storage.mvcc.reader import IsolationLevel

    try:
        return PointGetter(
            _DataView(snapshot, keymod), before_ts - 1, isolation=IsolationLevel.RC
        ).get(Key.from_encoded(enc_key))
    except Exception:  # noqa: BLE001 — old value is best-effort
        return None


class _DataView:
    """Engine snapshot with the z-prefix applied (observer reads applied state)."""

    def __init__(self, snap, keymod):
        self._snap = snap
        self._k = keymod

    def get_cf(self, cf, key):
        return self._snap.get_cf(cf, self._k.data_key(key))

    def cursor_cf(self, cf, lower=None, upper=None):
        from ..raft.raftkv import _PrefixCursor

        lo = self._k.data_key(lower) if lower is not None else self._k.DATA_MIN_KEY
        hi = self._k.data_key(upper) if upper is not None else self._k.DATA_MAX_KEY
        return _PrefixCursor(self._snap.cursor_cf(cf, lo, hi))

    def scan_cf(self, cf, start, end, limit=None, reverse=False):
        from ..storage.engine import Snapshot

        return Snapshot.scan_cf(self, cf, start, end, limit, reverse)


# ---------------------------------------------------------------------------
# Wire service (cdcpb ChangeData: service.rs register_region/EventFeed)
# ---------------------------------------------------------------------------


class SeqSink(Sink):
    """Sink with per-event sequence numbers so wire clients pull-resume
    (the push EventFeed stream adapted to the request/response transport:
    register → pull events after a seq → deregister).

    Flow control (channel.rs memory-quota sink): buffered bytes charge the
    shared ``quota``.  Delta events from the APPLY path must never block the
    apply worker — when the quota is exhausted the sink turns CONGESTED and
    the subscription is torn down on the next pull (the reference cancels
    the downstream, which re-registers and re-scans).  Incremental-scan
    emission instead PAUSES until the client drains.  Acked items release
    their reservation in drain_after."""

    def __init__(self, quota=None):
        super().__init__()
        self._seq = 0
        self._cv = threading.Condition(self._mu)
        self.quota = quota
        self.congested = False
        self.closed = False
        self.items: list[tuple[int, str, object, int]] = []  # (+byte size)

    def _push(self, kind: str, payload, size: int) -> bool:
        """Append under the sink lock, RE-CHECKING closed: close() freed the
        quota of everything it saw — an allocation pushed after that must be
        returned here or it leaks from the store-wide quota forever."""
        with self._cv:
            if self.closed:
                if self.quota is not None:
                    self.quota.free(size)
                return False
            self._seq += 1
            self.items.append((self._seq, kind, payload, size))
            self._cv.notify_all()
            return True

    def emit(self, event: ChangeEvent) -> None:
        if self.congested or self.closed:
            return  # tear-down already decided; rescan will recover these
        size = _event_bytes(event)
        if self.quota is not None and not self.quota.alloc(size):
            self.congested = True
            with self._cv:
                self._cv.notify_all()
            return
        self._push("event", event, size)

    def emit_scan(self, event: ChangeEvent) -> bool:
        size = _event_bytes(event)
        if self.quota is not None:
            # alloc OUTSIDE the sink lock: drain_after needs the lock to
            # free quota, so waiting under it would deadlock the pipeline
            ok = self.quota.alloc_wait(
                size, timeout=60.0,
                cancelled=lambda: self.closed or self.congested,
            )
            if not ok:
                self.congested = True
                return False
        return self._push("event", event, size)

    def emit_resolved(self, region_id: int, ts: int) -> None:
        if self.congested or self.closed:
            return
        if self.quota is not None:
            # watermarks are tiny and must not be dropped (force variant)
            self.quota.alloc_force(32)
        self._push("resolved", (region_id, ts), 32)

    def close(self) -> None:
        with self._cv:
            self.closed = True
            if self.quota is not None:
                for _seq, _kind, _payload, size in self.items:
                    self.quota.free(size)
            self.items.clear()
            self._cv.notify_all()

    def drain_after(
        self, after_seq: int, limit: int, timeout: float = 0.0
    ) -> list[tuple[int, str, object]]:
        with self._cv:
            # drop everything at or below the client's ack: memory stays
            # bounded by the client's pull cadence, quota freed with it
            freed = 0
            while self.items and self.items[0][0] <= after_seq:
                freed += self.items.pop(0)[3]
            if not self.items and timeout > 0 and not self.congested:
                # long-poll: the push EventFeed's latency without its stream
                if freed and self.quota is not None:
                    self.quota.free(freed)
                    freed = 0
                self._cv.wait(timeout)
                while self.items and self.items[0][0] <= after_seq:
                    freed += self.items.pop(0)[3]
            out = [(s, k, p) for s, k, p, _sz in self.items[:limit]]
        if freed and self.quota is not None:
            self.quota.free(freed)
        return out


class CdcService:
    """The ChangeData service surface: one observer shared by the store's
    apply pipeline, per-subscription SeqSinks, pull-based event feed."""

    def __init__(self, store, snapshot_fn=None, memory_quota_bytes: int = 64 << 20,
                 memory_trace=None):
        from ..util import keys as keymod
        from ..util.memory import MemoryQuota

        self.store = store
        # the store engine speaks the z-prefixed data keyspace; scans must see
        # user keys, exactly like the observer's old-value reads
        self._snapshot_fn = snapshot_fn or (
            lambda: _DataView(store.engine.snapshot(), keymod)
        )
        self._mu = threading.Lock()
        self._subs: dict[int, tuple[int, CdcObserver]] = {}  # sub_id -> (region, obs)
        self._last_pull: dict[int, float] = {}  # sub_id -> monotonic of last events()
        self._next_id = 0
        # ONE quota across every subscription's sink (channel.rs
        # MemoryQuota): a slow downstream cannot balloon this store
        self.quota = MemoryQuota(memory_quota_bytes)
        if memory_trace is not None:
            memory_trace.child("cdc_sinks", provider=self.quota.in_use)
        store.apply_observers.append(self._observe)

    def _observe(self, store, region, cmd):
        with self._mu:
            observers = [obs for _rid, obs in self._subs.values()]
        for obs in observers:
            obs.observe_apply(store, region, cmd)

    def register(self, region_id: int, checkpoint_ts: int) -> dict:
        """register_region: subscribe + incremental scan from the checkpoint
        (delta changes after checkpoint_ts stream via the observer)."""
        peer = self.store.peers.get(region_id)
        if peer is None:
            return {"error": {"other": f"region {region_id} not on this store"}}
        if not peer.node.is_leader():
            return {"error": {"not_leader": region_id}}
        obs = CdcObserver(sink=SeqSink(quota=self.quota))
        # install the delegate BEFORE taking the scan snapshot (the reference
        # does the same): an apply landing in between shows up as a delta
        # event — possibly duplicating a scan row, which is the documented
        # at-least-once overlap — instead of being silently lost
        with self._mu:
            self._next_id += 1
            sub_id = self._next_id
            self._subs[sub_id] = (region_id, obs)
            self._last_pull[sub_id] = time.monotonic()
        scanned = obs.incremental_scan(self._snapshot_fn(), region_id, checkpoint_ts)
        return {"sub_id": sub_id, "scanned": scanned}

    def events(
        self, sub_id: int, after_seq: int = 0, limit: int = 1024, timeout: float = 0.0
    ) -> dict:
        with self._mu:
            ent = self._subs.get(sub_id)
            if ent is not None:
                self._last_pull[sub_id] = time.monotonic()
        if ent is None:
            return {"error": {"other": f"unknown cdc subscription {sub_id}"}}
        region_id, obs = ent
        peer = self.store.peers.get(region_id)
        if peer is None or not peer.node.is_leader():
            # role changed: the reference tears the delegate down and the
            # client re-registers against the new leader
            self.deregister(sub_id)
            return {"error": {"not_leader": region_id}}
        if getattr(obs.sink, "congested", False):
            # the downstream fell too far behind and the buffer hit its
            # memory quota: cancel the subscription (the reference's
            # congested error) — the client re-registers and re-scans
            self.deregister(sub_id)
            return {"error": {"congested": region_id}}
        out = []
        last = after_seq
        for seq, kind, payload in obs.sink.drain_after(after_seq, limit, timeout):
            last = seq
            if kind == "event":
                e: ChangeEvent = payload
                out.append({
                    "seq": seq, "type": e.op, "key": e.key,
                    "value": e.value if e.value is not None else b"",
                    "old_value": e.old_value if e.old_value is not None else b"",
                    "start_ts": e.start_ts, "commit_ts": e.commit_ts,
                })
            else:
                rid, ts = payload
                out.append({"seq": seq, "type": "resolved", "region_id": rid, "ts": ts})
        return {"events": out, "last_seq": last}

    def resolved(self, sub_id: int, ts: int) -> dict:
        """Advance the subscription's resolved-ts watermark (the resolved-ts
        worker calls this; clients see it interleaved in the event feed)."""
        with self._mu:
            ent = self._subs.get(sub_id)
        if ent is None:
            return {"error": {"other": f"unknown cdc subscription {sub_id}"}}
        region_id, obs = ent
        obs.emit_resolved(region_id, ts)
        return {}

    def deregister(self, sub_id: int) -> dict:
        with self._mu:
            ent = self._subs.pop(sub_id, None)
            self._last_pull.pop(sub_id, None)
        if ent is not None:
            ent[1].unsubscribe(ent[0])
            close = getattr(ent[1].sink, "close", None)
            if close is not None:
                close()  # release the sink's quota reservation
        return {}

    def reap_idle(self, max_idle_s: float = 300.0) -> int:
        """Tear down subscriptions whose client stopped pulling: a vanished
        downstream must not hold its buffered bytes against the store-wide
        quota forever (the reference detects this via its gRPC stream
        closing; the pull transport needs an idle clock).  Call from the
        store heartbeat."""
        now = time.monotonic()
        with self._mu:
            stale = [sid for sid, t in self._last_pull.items()
                     if now - t > max_idle_s]
        for sid in stale:
            self.deregister(sid)
        return len(stale)
