"""Change data capture: stream committed row changes per region.

Re-expression of ``components/cdc`` (observer.rs CmdObserver; delegate.rs
per-region Delegate; endpoint.rs; old_value.rs): an apply observer watches
the raft apply stream, pairs prewrites with their commits, and emits ordered
row-change events (with old value) to downstream sinks; a new subscription
first runs an incremental scan of existing data at its start ts, then streams
live events gated by the resolver's resolved-ts watermark.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE
from ..storage.txn_types import Key, Lock, LockType, Write, WriteType, split_ts


@dataclass
class ChangeEvent:
    region_id: int
    key: bytes  # raw user key
    op: str  # "put" | "delete"
    value: bytes | None
    old_value: bytes | None
    start_ts: int
    commit_ts: int


class Sink:
    """Downstream consumer (channel.rs's memory-quota sink, simplified)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.events: list[ChangeEvent] = []
        self.resolved: list[tuple[int, int]] = []  # (region_id, resolved_ts)

    def emit(self, event: ChangeEvent) -> None:
        with self._mu:
            self.events.append(event)

    def emit_resolved(self, region_id: int, ts: int) -> None:
        with self._mu:
            self.resolved.append((region_id, ts))


class CdcDelegate:
    """Per-region capture state (delegate.rs:192): pending prewrites keyed by
    (key, start_ts) until their commit arrives."""

    def __init__(self, region_id: int, sink: Sink):
        self.region_id = region_id
        self.sink = sink
        self.pending: dict[tuple[bytes, int], tuple[str, bytes | None, bytes | None]] = {}

    def on_prewrite(self, key: bytes, lock: Lock, old_value: bytes | None) -> None:
        op = "delete" if lock.lock_type == LockType.DELETE else "put"
        self.pending[(key, lock.ts)] = (op, lock.short_value, old_value)

    def on_commit(self, key: bytes, write: Write, commit_ts: int) -> None:
        ent = self.pending.pop((key, write.start_ts), None)
        if write.write_type in (WriteType.ROLLBACK, WriteType.LOCK):
            # LOCK records come from lock-only/pessimistic commits — no data
            # change, so no event (delegate.rs filters them the same way)
            return
        if ent is None:
            # commit without observed prewrite (e.g. subscribed mid-txn)
            op = "delete" if write.write_type == WriteType.DELETE else "put"
            value, old = write.short_value, None
        else:
            op, value, old = ent
            if write.write_type == WriteType.DELETE:
                op = "delete"
        self.sink.emit(
            ChangeEvent(self.region_id, key, op, value, old, write.start_ts, commit_ts)
        )


class CdcObserver:
    """The raftstore apply observer wiring (observer.rs:26)."""

    def __init__(self, sink: Sink | None = None):
        self.sink = sink or Sink()
        self._mu = threading.Lock()
        self.delegates: dict[int, CdcDelegate] = {}
        self.subscribed: set[int] = set()

    def subscribe(self, region_id: int) -> CdcDelegate:
        with self._mu:
            self.subscribed.add(region_id)
            d = self.delegates.get(region_id)
            if d is None:
                d = CdcDelegate(region_id, self.sink)
                self.delegates[region_id] = d
            return d

    def unsubscribe(self, region_id: int) -> None:
        with self._mu:
            self.subscribed.discard(region_id)
            self.delegates.pop(region_id, None)

    def incremental_scan(self, snapshot, region_id: int, start_ts: int) -> int:
        """Emit existing committed data up to ``start_ts`` (scanner.rs)."""
        from ..storage.mvcc import ForwardScanner

        d = self.subscribe(region_id)
        n = 0
        for raw_key, value in ForwardScanner(snapshot, start_ts, None, None):
            self.sink.emit(
                ChangeEvent(region_id, raw_key, "put", value, None, 0, start_ts)
            )
            n += 1
        return n

    # -- raftstore observer hook -------------------------------------------

    def observe_apply(self, store, region, cmd: dict) -> None:
        with self._mu:
            d = self.delegates.get(region.id)
        if d is None or region.id not in self.subscribed:
            return
        # capture on the leader only — every replica applies the command, but
        # a subscription is served by the region leader (endpoint.rs keeps
        # delegates on leaders and unsubscribes on role change)
        peer = store.peers.get(region.id)
        if peer is None or not peer.node.is_leader():
            return
        snapshot = store.engine.snapshot()
        from ..util import keys as keymod

        ops = cmd.get("ops", ())
        # long values ride in CF_DEFAULT within the same command; index them
        # by encoded key+start_ts so prewrite events carry the real value
        defaults = {key: val for op, cf, key, val in ops if cf == CF_DEFAULT and op == "put"}
        from ..storage.txn_types import append_ts

        for op, cf, key, val in ops:
            if cf == CF_LOCK and op == "put":
                try:
                    lock = Lock.from_bytes(val)
                except ValueError:
                    continue
                if lock.lock_type in (LockType.PUT, LockType.DELETE):
                    raw = Key.from_encoded(key).to_raw()
                    old = _read_old_value(snapshot, keymod, key, lock.ts)
                    if lock.short_value is None and lock.lock_type == LockType.PUT:
                        lock.short_value = defaults.get(append_ts(key, lock.ts))
                    d.on_prewrite(raw, lock, old)
            elif cf == CF_WRITE and op == "put":
                user_enc, commit_ts = split_ts(key)
                try:
                    write = Write.from_bytes(val)
                except ValueError:
                    continue
                raw = Key.from_encoded(user_enc).to_raw()
                d.on_commit(raw, write, commit_ts)

    def emit_resolved(self, region_id: int, ts: int) -> None:
        self.sink.emit_resolved(region_id, ts)


def _read_old_value(snapshot, keymod, enc_key: bytes, before_ts: int) -> bytes | None:
    """old_value.rs: the committed value the prewrite overwrites."""
    from ..storage.mvcc import PointGetter
    from ..storage.mvcc.reader import IsolationLevel

    try:
        return PointGetter(
            _DataView(snapshot, keymod), before_ts - 1, isolation=IsolationLevel.RC
        ).get(Key.from_encoded(enc_key))
    except Exception:  # noqa: BLE001 — old value is best-effort
        return None


class _DataView:
    """Engine snapshot with the z-prefix applied (observer reads applied state)."""

    def __init__(self, snap, keymod):
        self._snap = snap
        self._k = keymod

    def get_cf(self, cf, key):
        return self._snap.get_cf(cf, self._k.data_key(key))

    def cursor_cf(self, cf, lower=None, upper=None):
        from ..raft.raftkv import _PrefixCursor

        lo = self._k.data_key(lower) if lower is not None else self._k.DATA_MIN_KEY
        hi = self._k.data_key(upper) if upper is not None else self._k.DATA_MAX_KEY
        return _PrefixCursor(self._snap.cursor_cf(cf, lo, hi))

    def scan_cf(self, cf, start, end, limit=None, reverse=False):
        from ..storage.engine import Snapshot

        return Snapshot.scan_cf(self, cf, start, end, limit, reverse)
