"""Consistent backup + restore through external storage.

Re-expression of ``components/backup`` (endpoint.rs:434 range-driven backup at
a backup_ts; writer.rs SST output) + ``components/sst_importer`` (download +
ingest) + ``components/external_storage`` (local backend).  A backup is a
consistent MVCC scan at ``backup_ts`` written as sorted KV files (our wire
framing standing in for SST); restore ingests them back as committed writes.
"""

from __future__ import annotations

import os

from ..storage.mvcc import ForwardScanner
from ..storage.txn_types import Key
from ..util import codec
from .importer import MAGIC, SstImporter  # noqa: F401 - SstImporter moved to
# importer.py (unbounded disk staging, raft ingest, duplicate detection);
# re-imported here because backup and restore share the file format and
# callers historically import both from this module


class ExternalStorage:
    """Pluggable blob store (external_storage: local/noop/S3)."""

    def write(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, name: str) -> bytes:
        raise NotImplementedError

    def list(self) -> list[str]:
        raise NotImplementedError


class LocalStorage(ExternalStorage):
    def __init__(self, base: str):
        self.base = base
        os.makedirs(base, exist_ok=True)

    def write(self, name: str, data: bytes) -> None:
        tmp = os.path.join(self.base, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(self.base, name))

    def read(self, name: str) -> bytes:
        with open(os.path.join(self.base, name), "rb") as f:
            return f.read()

    def delete(self, name: str) -> None:
        try:
            os.unlink(os.path.join(self.base, name))
        except FileNotFoundError:
            pass

    def list(self) -> list[str]:
        return sorted(n for n in os.listdir(self.base) if not n.endswith(".tmp"))


class NoopStorage(ExternalStorage):
    def write(self, name: str, data: bytes) -> None:
        pass

    def read(self, name: str) -> bytes:
        raise FileNotFoundError(name)

    def list(self) -> list[str]:
        return []


class RegionInfoAccessor:
    """Read-only view of the store's region set, ordered by start key
    (coprocessor/region_info_accessor.rs:494): the backup endpoint seeks
    from range start to the next region repeatedly instead of assuming one
    flat range."""

    def __init__(self, store):
        self.store = store

    def regions_in_range(self, start_raw: bytes | None, end_raw: bytes | None):
        """(region, peer, is_leader) for every region overlapping the RAW
        user-key range, sorted by region start."""
        start_enc = Key.from_raw(start_raw).encoded if start_raw else b""
        end_enc = Key.from_raw(end_raw).encoded if end_raw else None
        out = []
        for peer in list(self.store.peers.values()):
            region = peer.region
            r_start = region.start_key or b""
            r_end = region.end_key or None
            if end_enc is not None and r_start >= end_enc:
                continue
            if r_end is not None and r_end <= start_enc:
                continue
            out.append((region, peer, peer.node.is_leader()))
        out.sort(key=lambda t: t[0].start_key)
        return out


class BackupWriter:
    """Per-region backup file writer (backup/src/writer.rs): sorted entries
    in the shared importer framing, split at ``max_file_bytes``, each file
    carrying total_kvs / total_bytes / an order-independent crc64 the
    restore side (and ADMIN CHECKSUM) can verify."""

    def __init__(self, storage: ExternalStorage, name: str, backup_ts: int,
                 max_file_bytes: int = 64 << 20):
        from ..copr.analyze import crc64

        self._crc64 = crc64
        self.storage = storage
        self.name = name
        self.backup_ts = backup_ts
        self.max_file_bytes = max_file_bytes
        self.files: list[dict] = []
        self._buf = bytearray()
        self._n = 0
        self._bytes = 0
        self._crc = 0
        self._first: bytes | None = None
        self._last: bytes | None = None

    def _reset(self) -> None:
        self._buf = bytearray(MAGIC) + codec.encode_var_u64(self.backup_ts)
        self._n = 0
        self._bytes = 0
        self._crc = 0
        self._first = None
        self._last = None

    def add(self, raw_key: bytes, value: bytes) -> None:
        if not self._buf:
            self._reset()
        self._buf += codec.encode_compact_bytes(raw_key)
        self._buf += codec.encode_compact_bytes(value)
        if self._first is None:
            self._first = raw_key
        self._last = raw_key
        self._n += 1
        self._bytes += len(raw_key) + len(value)
        # XOR-combined per-entry crc64 (checksum.rs): order independent, so
        # per-file sums merge into range/region/cluster checksums
        self._crc ^= self._crc64(
            codec.encode_compact_bytes(raw_key) + codec.encode_compact_bytes(value)
        )
        if len(self._buf) >= self.max_file_bytes:
            self.flush()

    def flush(self) -> dict | None:
        if not self._buf or self._n == 0:
            self._buf = bytearray()
            return None
        fname = f"{self.name}_{len(self.files):04d}.bak"
        self.storage.write(fname, bytes(self._buf))
        meta = {
            "file": fname,
            "total_kvs": self._n,
            "total_bytes": self._bytes,
            "crc64xor": self._crc,
            "start_key": (self._first or b"").hex(),
            "end_key": (self._last or b"").hex(),
        }
        self.files.append(meta)
        self._buf = bytearray()
        return meta


class BackupEndpoint:
    def __init__(self, storage: ExternalStorage):
        self.storage = storage

    def backup(self, store, name: str, backup_ts: int,
               start: bytes | None = None, end: bytes | None = None,
               max_file_bytes: int = 64 << 20, snapshot_fn=None) -> dict:
        """Region-progress-driven backup (endpoint.rs:434): walk the store's
        regions across [start, end) via the RegionInfoAccessor, scan each
        LEADER region consistently at backup_ts through its own region
        snapshot, and emit size-split, checksummed files plus a backupmeta
        the restore side drives from."""
        from ..raft.raftkv import RegionSnapshot

        accessor = RegionInfoAccessor(store)
        jobs = []
        for region, peer, is_leader in accessor.regions_in_range(start, end):
            if not is_leader:
                continue  # that region's leader store backs it up
            if snapshot_fn is not None:
                snap = snapshot_fn(peer)
            else:
                snap = RegionSnapshot(store.engine.snapshot(), region.clone())
            jobs.append((region, snap))
        lo = Key.from_raw(start) if start else None
        hi = Key.from_raw(end) if end else None
        return self._backup_regions(jobs, name, backup_ts, max_file_bytes, lo, hi)

    def backup_offline(self, engine, name: str, backup_ts: int,
                       max_file_bytes: int = 64 << 20) -> dict:
        """Backup a STOPPED store's engine directly (the tikv-ctl / BR
        offline flow): regions enumerate from persisted CF_RAFT meta —
        leadership is irrelevant with no live traffic — and each scans
        through its own RegionSnapshot exactly like the online path.
        A dir with NO region meta is refused: it is not a store."""
        from ..raft.raftkv import RegionSnapshot
        from ..raft.store import decode_region, scan_region_states

        regions = [decode_region(v)[0] for _rid, v in
                   scan_region_states(engine.snapshot())]
        if not regions:
            raise ValueError(
                "no region metadata found — not a (bootstrapped) store dir")
        regions.sort(key=lambda r: r.start_key)
        jobs = [(r, RegionSnapshot(engine.snapshot(), r.clone())) for r in regions]
        return self._backup_regions(jobs, name, backup_ts, max_file_bytes, None, None)

    def _backup_regions(self, jobs, name: str, backup_ts: int,
                        max_file_bytes: int, lo, hi) -> dict:
        """ONE definition of the per-region write loop + meta accumulation,
        shared by the online and offline flows.  Leftover prewrite locks
        abort with a clear remedy and every partial file is removed — a
        backup without its meta must not masquerade as one."""
        import json as _json

        from ..storage.mvcc.reader import KeyIsLockedError

        regions_meta = []
        total = {"kvs": 0, "bytes": 0, "crc64xor": 0}
        written: list[str] = []
        try:
            for region, snap in jobs:
                writer = BackupWriter(self.storage, f"{name}_r{region.id}",
                                      backup_ts, max_file_bytes)
                for raw_key, value in ForwardScanner(snap, backup_ts, lo, hi):
                    writer.add(raw_key, value)
                writer.flush()
                written.extend(f["file"] for f in writer.files)
                for f in writer.files:
                    total["kvs"] += f["total_kvs"]
                    total["bytes"] += f["total_bytes"]
                    total["crc64xor"] ^= f["crc64xor"]
                regions_meta.append({
                    "region_id": region.id,
                    "start_key": (region.start_key or b"").hex(),
                    "end_key": (region.end_key or b"").hex(),
                    "files": writer.files,
                })
        except KeyIsLockedError as e:
            for fname in written:
                delete = getattr(self.storage, "delete", None)
                if delete is not None:
                    delete(fname)
            raise ValueError(
                f"backup aborted: prewrite lock below backup_ts on "
                f"{getattr(e, 'key', b'?')!r} — resolve locks first "
                f"(ctl resolve-lock / recover-mvcc)") from e
        meta = {
            "name": name,
            "backup_ts": backup_ts,
            "regions": regions_meta,
            "total_kvs": total["kvs"],
            "total_bytes": total["bytes"],
            "crc64xor": total["crc64xor"],
        }
        self.storage.write(f"{name}.backupmeta", _json.dumps(meta).encode())
        return meta

    def verify(self, name: str) -> dict:
        """Re-read every file of a backup and recompute its checksums
        against the meta (the BR validate flow)."""
        import json as _json

        from ..copr.analyze import crc64

        meta = _json.loads(self.storage.read(f"{name}.backupmeta"))
        checked = 0
        for region in meta["regions"]:
            for f in region["files"]:
                data = self.storage.read(f["file"])
                if not data.startswith(MAGIC):
                    raise ValueError(f"{f['file']}: bad magic")
                off = len(MAGIC)
                _ts, off = codec.decode_var_u64(data, off)
                crc = 0
                n = 0
                while off < len(data):
                    k, off = codec.decode_compact_bytes(data, off)
                    v, off = codec.decode_compact_bytes(data, off)
                    crc ^= crc64(codec.encode_compact_bytes(k)
                                 + codec.encode_compact_bytes(v))
                    n += 1
                if n != f["total_kvs"] or crc != f["crc64xor"]:
                    raise ValueError(
                        f"{f['file']}: checksum mismatch "
                        f"(kvs {n}/{f['total_kvs']}, crc {crc:x}/{f['crc64xor']:x})")
                checked += 1
        return {"files": checked, "total_kvs": meta["total_kvs"],
                "crc64xor": meta["crc64xor"]}

    def restore(self, engine, name: str, restore_ts: int, keys_mgr=None) -> dict:
        """Meta-driven restore of every file (BR restore loop): each file
        re-enters the store as committed writes at restore_ts."""
        import json as _json

        meta = _json.loads(self.storage.read(f"{name}.backupmeta"))
        # staged restore files are encryption-at-rest surface: on an
        # encrypted store they seal under its DataKeyManager
        imp = SstImporter(self.storage, keys_mgr=keys_mgr)
        restored = 0
        for region in meta["regions"]:
            for f in region["files"]:
                r = imp.restore(engine, f["file"], restore_ts)
                restored += r.get("kvs", 0)
        return {"kvs": restored, "files": sum(len(r["files"]) for r in meta["regions"])}

    def backup_range(
        self,
        snapshot,
        name: str,
        backup_ts: int,
        start: bytes | None = None,
        end: bytes | None = None,
    ) -> dict:
        """Consistent scan at backup_ts → one backup file. Returns meta."""
        out = bytearray(MAGIC)
        out += codec.encode_var_u64(backup_ts)
        n = 0
        scanner = ForwardScanner(
            snapshot,
            backup_ts,
            Key.from_raw(start) if start else None,
            Key.from_raw(end) if end else None,
        )
        for raw_key, value in scanner:
            out += codec.encode_compact_bytes(raw_key)
            out += codec.encode_compact_bytes(value)
            n += 1
        self.storage.write(name, bytes(out))
        return {"file": name, "kvs": n, "backup_ts": backup_ts}


