"""Consistent backup + restore through external storage.

Re-expression of ``components/backup`` (endpoint.rs:434 range-driven backup at
a backup_ts; writer.rs SST output) + ``components/sst_importer`` (download +
ingest) + ``components/external_storage`` (local backend).  A backup is a
consistent MVCC scan at ``backup_ts`` written as sorted KV files (our wire
framing standing in for SST); restore ingests them back as committed writes.
"""

from __future__ import annotations

import os

from ..storage.engine import CF_DEFAULT, CF_WRITE, WriteBatch
from ..storage.mvcc import ForwardScanner
from ..storage.txn_types import Key, Write, WriteType
from ..util import codec

MAGIC = b"TPUBK1\n"


class ExternalStorage:
    """Pluggable blob store (external_storage: local/noop/S3)."""

    def write(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, name: str) -> bytes:
        raise NotImplementedError

    def list(self) -> list[str]:
        raise NotImplementedError


class LocalStorage(ExternalStorage):
    def __init__(self, base: str):
        self.base = base
        os.makedirs(base, exist_ok=True)

    def write(self, name: str, data: bytes) -> None:
        tmp = os.path.join(self.base, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(self.base, name))

    def read(self, name: str) -> bytes:
        with open(os.path.join(self.base, name), "rb") as f:
            return f.read()

    def list(self) -> list[str]:
        return sorted(n for n in os.listdir(self.base) if not n.endswith(".tmp"))


class NoopStorage(ExternalStorage):
    def write(self, name: str, data: bytes) -> None:
        pass

    def read(self, name: str) -> bytes:
        raise FileNotFoundError(name)

    def list(self) -> list[str]:
        return []


class BackupEndpoint:
    def __init__(self, storage: ExternalStorage):
        self.storage = storage

    def backup_range(
        self,
        snapshot,
        name: str,
        backup_ts: int,
        start: bytes | None = None,
        end: bytes | None = None,
    ) -> dict:
        """Consistent scan at backup_ts → one backup file. Returns meta."""
        out = bytearray(MAGIC)
        out += codec.encode_var_u64(backup_ts)
        n = 0
        scanner = ForwardScanner(
            snapshot,
            backup_ts,
            Key.from_raw(start) if start else None,
            Key.from_raw(end) if end else None,
        )
        for raw_key, value in scanner:
            out += codec.encode_compact_bytes(raw_key)
            out += codec.encode_compact_bytes(value)
            n += 1
        self.storage.write(name, bytes(out))
        return {"file": name, "kvs": n, "backup_ts": backup_ts}


class SstImporter:
    """Restore: download backup files and ingest as committed writes at a
    fresh ts (sst_importer download:308 + ingest:158; ranges may be rewritten
    by a key-prefix mapping like the reference's rewrite rules)."""

    _STAGE_MAX = 16  # staged files are bounded; oldest evicted (ingest pops)

    def __init__(self, storage: ExternalStorage):
        self.storage = storage
        import threading

        self._mu = threading.Lock()
        self._staged: dict[str, bytes] = {}
        # Rewrite rule registered at download time, kept (bounded, but far
        # larger than the staged-bytes cap) even after the staged bytes are
        # evicted: a fallback re-read of the source must re-apply the same
        # rewrite, never silently ingest un-rewritten keys.
        self._rewrites: dict[str, tuple[bytes, bytes] | None] = {}

    @staticmethod
    def _iter_entries(data: bytes, rewrite: tuple[bytes, bytes] | None):
        """Parse a backup payload: yields (raw_key, value) with the rewrite
        rule applied — the ONE definition of the file format + rewrite
        semantics, shared by download and restore."""
        if not data.startswith(MAGIC):
            raise ValueError("not a backup file")
        off = len(MAGIC)
        backup_ts, off = codec.decode_var_u64(data, off)
        while off < len(data):
            raw_key, off = codec.decode_compact_bytes(data, off)
            value, off = codec.decode_compact_bytes(data, off)
            if rewrite is not None and raw_key.startswith(rewrite[0]):
                raw_key = rewrite[1] + raw_key[len(rewrite[0]):]
            yield raw_key, value

    def download(self, name: str, rewrite: tuple[bytes, bytes] | None = None) -> dict:
        """Fetch + validate + REWRITE a backup file ahead of ingest
        (sst_service.rs download:308 applies the rewrite rules at download
        time): the staged bytes are final, so ingest is a pure engine
        write."""
        data = self.storage.read(name)
        out = bytearray(MAGIC)
        off = len(MAGIC)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        backup_ts, hoff = codec.decode_var_u64(data, off)
        out += codec.encode_var_u64(backup_ts)
        n = 0
        for raw_key, value in self._iter_entries(data, rewrite):
            out += codec.encode_compact_bytes(raw_key)
            out += codec.encode_compact_bytes(value)
            n += 1
        with self._mu:
            # pop-then-insert: eviction order is by latest download, so a
            # re-downloaded name moves to the back of the FIFO
            self._staged.pop(name, None)
            while len(self._staged) >= self._STAGE_MAX:
                self._staged.pop(next(iter(self._staged)))
            self._staged[name] = bytes(out)
            self._rewrites.pop(name, None)
            while len(self._rewrites) >= 64 * self._STAGE_MAX:
                self._rewrites.pop(next(iter(self._rewrites)))
            self._rewrites[name] = rewrite
        return {"file": name, "kvs": n, "backup_ts": backup_ts}

    def restore(
        self,
        engine,
        name: str,
        restore_ts: int,
        ctx: dict | None = None,
        rewrite: tuple[bytes, bytes] | None = None,
    ) -> dict:
        with self._mu:
            data = self._staged.get(name)  # read, don't pop: a failed
            # ingest must retry against the SAME (rewritten) staged bytes,
            # never silently fall back to the un-rewritten source
            recorded_rewrite = self._rewrites.get(name)
        staged = data is not None
        if staged:
            rewrite = None  # staged bytes were rewritten at download time
        else:
            if rewrite is None and recorded_rewrite is not None:
                # Staged bytes were evicted after download: re-read the
                # source and re-apply the rewrite registered at download
                # time, so an eviction can never ingest un-rewritten keys.
                # An EXPLICIT ingest-time rewrite still wins — the caller
                # may deliberately re-ingest under a different prefix.
                rewrite = recorded_rewrite
            data = self.storage.read(name)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        wb = WriteBatch()
        n = 0
        for raw_key, value in self._iter_entries(data, rewrite):
            k = Key.from_raw(raw_key)
            if len(value) <= 255:
                w = Write(WriteType.PUT, restore_ts, short_value=value)
            else:
                w = Write(WriteType.PUT, restore_ts)
                wb.put_cf(CF_DEFAULT, k.append_ts(restore_ts).encoded, value)
            wb.put_cf(CF_WRITE, k.append_ts(restore_ts + 1).encoded, w.to_bytes())
            n += 1
        engine.write(ctx, wb)
        if staged:
            with self._mu:
                self._staged.pop(name, None)  # drop only after success
        return {"file": name, "kvs": n, "restored_at": restore_ts + 1}

    def restore_via_sst(
        self,
        engine,
        name: str,
        restore_ts: int,
        rewrite: tuple[bytes, bytes] | None = None,
        workdir: str | None = None,
    ) -> dict:
        """Bulk restore straight into a NATIVE engine via SST ingest
        (sst_importer's real shape: build sorted immutable files, AddFile
        them) — bypasses the per-record WriteBatch path, so a large restore
        costs one file copy + one WAL reference instead of N WAL records.
        Only for engine-local loads (bench/bootstrap); replicated restores
        keep the raft propose path in ``restore``."""
        import tempfile

        from ..native.engine import build_sst

        # same staged-bytes discipline as restore(): staged data was already
        # rewritten at download time; if evicted, the rewrite recorded at
        # download is re-applied so eviction can never ingest un-rewritten
        # keys (an explicit caller rewrite still wins)
        with self._mu:
            data = self._staged.get(name)
            recorded_rewrite = self._rewrites.get(name)
        if data is not None:
            rewrite = None
        else:
            if rewrite is None and recorded_rewrite is not None:
                rewrite = recorded_rewrite
            data = self.storage.read(name)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        default_rows: list[tuple[bytes, bytes]] = []
        write_rows: list[tuple[bytes, bytes]] = []
        n = 0
        for raw_key, value in self._iter_entries(data, rewrite):
            k = Key.from_raw(raw_key)
            if len(value) <= 255:
                w = Write(WriteType.PUT, restore_ts, short_value=value)
            else:
                w = Write(WriteType.PUT, restore_ts)
                default_rows.append((k.append_ts(restore_ts).encoded, value))
            write_rows.append((k.append_ts(restore_ts + 1).encoded, w.to_bytes()))
            n += 1
        entries = [("default", k, v) for k, v in sorted(default_rows)]
        entries += [("write", k, v) for k, v in sorted(write_rows)]
        fd, path = tempfile.mkstemp(suffix=".sst", dir=workdir)
        os.close(fd)
        try:
            build_sst(path, entries)
            engine.ingest_sst(path)
        finally:
            os.unlink(path)
        return {"file": name, "kvs": n, "restored_at": restore_ts + 1, "via": "sst"}
