"""Consistent backup + restore through external storage.

Re-expression of ``components/backup`` (endpoint.rs:434 range-driven backup at
a backup_ts; writer.rs SST output) + ``components/sst_importer`` (download +
ingest) + ``components/external_storage`` (local backend).  A backup is a
consistent MVCC scan at ``backup_ts`` written as sorted KV files (our wire
framing standing in for SST); restore ingests them back as committed writes.
"""

from __future__ import annotations

import os

from ..storage.mvcc import ForwardScanner
from ..storage.txn_types import Key
from ..util import codec
from .importer import MAGIC, SstImporter  # noqa: F401 - SstImporter moved to
# importer.py (unbounded disk staging, raft ingest, duplicate detection);
# re-imported here because backup and restore share the file format and
# callers historically import both from this module


class ExternalStorage:
    """Pluggable blob store (external_storage: local/noop/S3)."""

    def write(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, name: str) -> bytes:
        raise NotImplementedError

    def list(self) -> list[str]:
        raise NotImplementedError


class LocalStorage(ExternalStorage):
    def __init__(self, base: str):
        self.base = base
        os.makedirs(base, exist_ok=True)

    def write(self, name: str, data: bytes) -> None:
        tmp = os.path.join(self.base, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(self.base, name))

    def read(self, name: str) -> bytes:
        with open(os.path.join(self.base, name), "rb") as f:
            return f.read()

    def list(self) -> list[str]:
        return sorted(n for n in os.listdir(self.base) if not n.endswith(".tmp"))


class NoopStorage(ExternalStorage):
    def write(self, name: str, data: bytes) -> None:
        pass

    def read(self, name: str) -> bytes:
        raise FileNotFoundError(name)

    def list(self) -> list[str]:
        return []


class BackupEndpoint:
    def __init__(self, storage: ExternalStorage):
        self.storage = storage

    def backup_range(
        self,
        snapshot,
        name: str,
        backup_ts: int,
        start: bytes | None = None,
        end: bytes | None = None,
    ) -> dict:
        """Consistent scan at backup_ts → one backup file. Returns meta."""
        out = bytearray(MAGIC)
        out += codec.encode_var_u64(backup_ts)
        n = 0
        scanner = ForwardScanner(
            snapshot,
            backup_ts,
            Key.from_raw(start) if start else None,
            Key.from_raw(end) if end else None,
        )
        for raw_key, value in scanner:
            out += codec.encode_compact_bytes(raw_key)
            out += codec.encode_compact_bytes(value)
            n += 1
        self.storage.write(name, bytes(out))
        return {"file": name, "kvs": n, "backup_ts": backup_ts}


