"""Cloud external-storage backends: S3, GCS, Azure-blob-style.

Re-expression of ``components/cloud`` (aws/src/s3.rs S3Storage with SigV4
request signing, gcp/src/gcs.rs GcsStorage over the JSON API,
azure/src/azblob.rs) + ``components/external_storage`` (create_storage by
URL: external_storage/src/lib.rs).  Pure stdlib (http.client + hmac): the
reference signs requests itself through rusoto's credential plumbing; here
SigV4 is implemented directly so the backend talks to any S3-compatible
endpoint (AWS, MinIO, an in-process test server) with no SDK.

All backends speak the ExternalStorage trait from ``backup.py`` so backup /
restore / import run over them unchanged.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import json
import time
import urllib.parse

from .backup import ExternalStorage, LocalStorage, NoopStorage


class CloudError(Exception):
    pass


def _retry(fn, attempts: int = 3, base_delay: float = 0.05):
    """Transient-error retry with exponential backoff (cloud/src/lib.rs
    RetryError semantics: 5xx and connection failures retry, 4xx do not)."""
    last: Exception | None = None
    for i in range(attempts):
        try:
            return fn()
        except CloudError as e:
            if not getattr(e, "retryable", False):
                raise
            last = e
        except FileNotFoundError:
            raise  # a definitive 404, not a transient fault
        except (ConnectionError, OSError) as e:
            last = e
        time.sleep(base_delay * (2**i))
    raise CloudError(f"retries exhausted: {last}")


def _http_error(status: int, body: bytes) -> CloudError:
    err = CloudError(f"HTTP {status}: {body[:200]!r}")
    # 5xx and 429 (rate limit) back off and retry; other 4xx are permanent
    err.retryable = status >= 500 or status == 429
    return err


# ---------------------------------------------------------------------------
# S3 (SigV4)
# ---------------------------------------------------------------------------


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac_sha256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Storage(ExternalStorage):
    """S3-compatible blob store with AWS Signature Version 4
    (cloud/aws/src/s3.rs; the signing recipe is the public SigV4 spec).

    ``endpoint`` may point at AWS, MinIO, or any S3-compatible server;
    ``multipart_threshold`` switches large writes to the multipart-upload
    flow (CreateMultipartUpload / UploadPart / CompleteMultipartUpload) the
    way the reference streams SST files."""

    def __init__(
        self,
        bucket: str,
        prefix: str = "",
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        endpoint: str = "http://127.0.0.1:9000",
        multipart_threshold: int = 8 * 1024 * 1024,
    ):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        u = urllib.parse.urlparse(endpoint)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.https = u.scheme == "https"
        self.multipart_threshold = multipart_threshold

    # -- signing ------------------------------------------------------------

    def _sign(self, method: str, path: str, query: str, payload: bytes, now: float | None = None) -> dict:
        t = time.gmtime(now if now is not None else time.time())
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
        datestamp = time.strftime("%Y%m%d", t)
        payload_hash = _sha256_hex(payload)
        host = f"{self.host}:{self.port}"
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        signed = ";".join(sorted(headers))
        canonical = "\n".join(
            [
                method,
                path,
                query,
                "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
                signed,
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join(
            ["AWS4-HMAC-SHA256", amz_date, scope, _sha256_hex(canonical.encode())]
        )
        k = _hmac_sha256(b"AWS4" + self.secret_key.encode(), datestamp)
        k = _hmac_sha256(k, self.region)
        k = _hmac_sha256(k, "s3")
        k = _hmac_sha256(k, "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        return headers

    def _request(self, method: str, key: str, payload: bytes = b"", query: dict | None = None) -> tuple[int, bytes, dict]:
        path = "/" + urllib.parse.quote(f"{self.bucket}/{key}" if key else self.bucket)
        # SigV4 canonicalization requires %20 for spaces, never '+'
        qs = urllib.parse.urlencode(sorted((query or {}).items()), quote_via=urllib.parse.quote)
        headers = self._sign(method, path, qs, payload)
        cls = http.client.HTTPSConnection if self.https else http.client.HTTPConnection
        conn = cls(self.host, self.port, timeout=30)
        try:
            conn.request(method, path + ("?" + qs if qs else ""), body=payload, headers=headers)
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, body, dict(resp.getheaders())
        finally:
            conn.close()

    def _key(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    # -- trait --------------------------------------------------------------

    def write(self, name: str, data: bytes) -> None:
        if len(data) > self.multipart_threshold:
            self._multipart_write(name, data)
            return

        def put():
            status, body, _ = self._request("PUT", self._key(name), data)
            if status not in (200, 201):
                raise _http_error(status, body)

        _retry(put)

    def _multipart_write(self, name: str, data: bytes) -> None:
        key = self._key(name)

        def create():
            st, bd, _ = self._request("POST", key, query={"uploads": ""})
            if st != 200:
                raise _http_error(st, bd)
            return bd.decode().split("<UploadId>")[1].split("</UploadId>")[0]

        upload_id = _retry(create)
        try:
            etags = []
            part = 1
            for off in range(0, len(data), self.multipart_threshold):
                chunk = data[off : off + self.multipart_threshold]

                def up(part=part, chunk=chunk):
                    st, bd, hd = self._request(
                        "PUT", key, chunk, query={"partNumber": str(part), "uploadId": upload_id}
                    )
                    if st != 200:
                        raise _http_error(st, bd)
                    for hk, hv in hd.items():
                        if hk.lower() == "etag":
                            return hv
                    return '""'

                etags.append(_retry(up))
                part += 1
            complete = "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{t}</ETag></Part>"
                for i, t in enumerate(etags)
            ) + "</CompleteMultipartUpload>"

            def done():
                st, bd, _ = self._request("POST", key, complete.encode(), query={"uploadId": upload_id})
                if st != 200:
                    raise _http_error(st, bd)

            _retry(done)
        except BaseException:
            # AbortMultipartUpload: real S3 bills orphaned parts forever
            try:
                self._request("DELETE", key, query={"uploadId": upload_id})
            except Exception:
                pass
            raise

    def read(self, name: str) -> bytes:
        def get():
            status, body, _ = self._request("GET", self._key(name))
            if status == 404:
                raise FileNotFoundError(name)
            if status != 200:
                raise _http_error(status, body)
            return body

        return _retry(get)

    def list(self) -> list[str]:
        from xml.sax.saxutils import unescape

        def ls():
            names = []
            token = None
            while True:  # ListObjectsV2 pages at 1000 keys
                q = {"list-type": "2"}
                if self.prefix:
                    q["prefix"] = self.prefix + "/"
                if token:
                    q["continuation-token"] = token
                status, body, _ = self._request("GET", "", query=q)
                if status != 200:
                    raise _http_error(status, body)
                text = body.decode()
                for part in text.split("<Key>")[1:]:
                    k = unescape(part.split("</Key>")[0])
                    if self.prefix:
                        k = k[len(self.prefix) + 1 :]
                    names.append(k)
                if "<IsTruncated>true</IsTruncated>" in text:
                    token = unescape(
                        text.split("<NextContinuationToken>")[1].split("</NextContinuationToken>")[0]
                    )
                else:
                    return sorted(names)

        return _retry(ls)


# ---------------------------------------------------------------------------
# GCS (JSON API)
# ---------------------------------------------------------------------------


class GcsStorage(ExternalStorage):
    """Google Cloud Storage over the JSON/upload API with bearer-token auth
    (cloud/gcp/src/gcs.rs; token provider pluggable the way the reference
    abstracts over service-account credentials)."""

    def __init__(
        self,
        bucket: str,
        prefix: str = "",
        token_provider=None,
        endpoint: str = "https://storage.googleapis.com",
    ):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.token_provider = token_provider or (lambda: "")
        u = urllib.parse.urlparse(endpoint)
        self.host = u.hostname or "storage.googleapis.com"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.https = u.scheme == "https"

    def _request(self, method: str, path: str, payload: bytes = b"", query: str = "") -> tuple[int, bytes]:
        headers = {"authorization": f"Bearer {self.token_provider()}"}
        cls = http.client.HTTPSConnection if self.https else http.client.HTTPConnection
        conn = cls(self.host, self.port, timeout=30)
        try:
            conn.request(method, path + ("?" + query if query else ""), body=payload, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _object(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    def write(self, name: str, data: bytes) -> None:
        obj = urllib.parse.quote(self._object(name), safe="")

        def put():
            status, body = self._request(
                "POST", f"/upload/storage/v1/b/{self.bucket}/o", data,
                query=f"uploadType=media&name={obj}",
            )
            if status != 200:
                raise _http_error(status, body)

        _retry(put)

    def read(self, name: str) -> bytes:
        obj = urllib.parse.quote(self._object(name), safe="")

        def get():
            status, body = self._request("GET", f"/storage/v1/b/{self.bucket}/o/{obj}", query="alt=media")
            if status == 404:
                raise FileNotFoundError(name)
            if status != 200:
                raise _http_error(status, body)
            return body

        return _retry(get)

    def list(self) -> list[str]:
        def ls():
            names = []
            token = ""
            while True:  # JSON API pages via nextPageToken
                q = f"prefix={urllib.parse.quote(self.prefix + '/', safe='')}" if self.prefix else ""
                if token:
                    q += ("&" if q else "") + f"pageToken={urllib.parse.quote(token, safe='')}"
                status, body = self._request("GET", f"/storage/v1/b/{self.bucket}/o", query=q)
                if status != 200:
                    raise _http_error(status, body)
                doc = json.loads(body or b"{}")
                for it in doc.get("items", []):
                    n = it["name"]
                    names.append(n[len(self.prefix) + 1 :] if self.prefix else n)
                token = doc.get("nextPageToken", "")
                if not token:
                    return sorted(names)

        return _retry(ls)


# ---------------------------------------------------------------------------
# URL factory
# ---------------------------------------------------------------------------


def create_storage(url: str, **kwargs) -> ExternalStorage:
    """Build a backend from a storage URL (external_storage/src/lib.rs
    create_storage): local:///path, noop://, s3://bucket/prefix,
    gcs://bucket/prefix.  Connection options (keys, region, endpoint, token
    provider) come in as kwargs, mirroring the reference's BackendConfig."""
    u = urllib.parse.urlparse(url)
    scheme = u.scheme or "local"
    if scheme == "local":
        return LocalStorage(u.path or u.netloc)
    if scheme == "noop":
        return NoopStorage()
    prefix = u.path.strip("/")
    if scheme == "s3":
        return S3Storage(bucket=u.netloc, prefix=prefix, **kwargs)
    if scheme in ("gcs", "gs"):
        return GcsStorage(bucket=u.netloc, prefix=prefix, **kwargs)
    raise ValueError(f"unknown storage scheme {scheme!r}")
