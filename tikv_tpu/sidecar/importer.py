"""SST import pipeline: download → stage on disk → ingest via raft.

Re-expression of ``sst_importer/src/sst_importer.rs`` (download:99/308 with
rewrite rules, ingest:132/481) and ``src/import/duplicate_detect.rs``, split
out of the backup sidecar:

* staging is DISK-spooled and unbounded in count — a restore of hundreds of
  files never evicts a staged file (the reference stages to the import dir
  on disk the same way); a staged file is deleted only after its successful
  ingest or an explicit cleanup
* ingest into a replicated store goes through a raft ``ingest_sst`` admin
  command whose log entry carries the final (rewritten) entries, so every
  replica — including one that was down and replays the log later — applies
  identical bytes (fsm/apply.rs:1427-1445 exec_ingest_sst)
* duplicate detection scans the target range's committed MVCC versions and
  reports keys the import would collide with (duplicate_detect.rs role)
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading

from ..storage.engine import CF_DEFAULT, CF_WRITE, WriteBatch
from ..storage.txn_types import MAX_TS, Key, Write, WriteType, split_ts
from ..util import codec

MAGIC = b"TPUBK1\n"  # backup/import file magic (one definition, shared with backup.py)


def encode_ingest_entries(entries: list[tuple[str, bytes, bytes]]) -> bytes:
    """The ingest_sst admin payload: count | (cf | key | value)*."""
    out = bytearray()
    out += codec.encode_var_u64(len(entries))
    for cf, key, val in entries:
        out += codec.encode_compact_bytes(cf.encode())
        out += codec.encode_compact_bytes(key)
        out += codec.encode_compact_bytes(val)
    return bytes(out)


class SstImporter:
    """Restore importer: download backup files (applying key rewrite rules at
    download time, sst_importer.rs:99), stage them on disk, ingest as
    committed writes at a fresh ts."""

    def __init__(self, storage, workdir: str | None = None, keys_mgr=None):
        # staged files are encryption-at-rest surface (import/sst_importer's
        # temp SSTs): sealed under the store's current data key when a
        # DataKeyManager is attached, with the key id framed for rotation
        self.keys_mgr = keys_mgr
        self.storage = storage
        self.workdir = workdir or tempfile.mkdtemp(prefix="tikv-import-")
        os.makedirs(self.workdir, exist_ok=True)
        self._mu = threading.Lock()
        # name -> staged path; unbounded count — files live on disk, not RAM
        self._staged: dict[str, str] = {}
        self._rewrites: dict[str, tuple[bytes, bytes] | None] = {}

    # -- download ------------------------------------------------------------

    @staticmethod
    def _iter_entries(data: bytes, rewrite: tuple[bytes, bytes] | None):
        """Parse a backup payload: yields (raw_key, value) with the rewrite
        rule applied — the ONE definition of the file format + rewrite
        semantics shared by download, restore, and duplicate detection."""
        if not data.startswith(MAGIC):
            raise ValueError("not a backup file")
        off = len(MAGIC)
        backup_ts, off = codec.decode_var_u64(data, off)
        while off < len(data):
            raw_key, off = codec.decode_compact_bytes(data, off)
            value, off = codec.decode_compact_bytes(data, off)
            if rewrite is not None and raw_key.startswith(rewrite[0]):
                raw_key = rewrite[1] + raw_key[len(rewrite[0]):]
            yield raw_key, value

    _STAGED_ENC = b"ENCS"

    def _seal_staged(self, data: bytes) -> bytes:
        if self.keys_mgr is None:
            return data
        from ..storage.encryption import seal

        kid, key = self.keys_mgr.current()
        return self._STAGED_ENC + codec.encode_var_u64(kid) + seal(key, data)

    def _unseal_staged(self, data: bytes) -> bytes:
        if not data.startswith(self._STAGED_ENC):
            return data  # staged before encryption was enabled
        if self.keys_mgr is None:
            raise ValueError("encrypted staged file but no key manager")
        from ..storage.encryption import unseal

        kid, off = codec.decode_var_u64(data, len(self._STAGED_ENC))
        return unseal(self.keys_mgr.by_id(kid), data[off:])

    def _staged_name(self, name: str) -> str:
        # a digest suffix keeps distinct names distinct ("a/b" vs "a_b"
        # must never collide on one staged path)
        digest = hashlib.sha256(name.encode()).hexdigest()[:12]
        return os.path.join(
            self.workdir, f"{name.replace('/', '_')}-{digest}.staged")

    def download(self, name: str, rewrite: tuple[bytes, bytes] | None = None) -> dict:
        """Fetch + validate + REWRITE a backup file ahead of ingest: the
        staged bytes on disk are final, so ingest is a pure write."""
        data = self.storage.read(name)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        backup_ts, _ = codec.decode_var_u64(data, len(MAGIC))
        out = bytearray(MAGIC)
        out += codec.encode_var_u64(backup_ts)
        n = 0
        for raw_key, value in self._iter_entries(data, rewrite):
            out += codec.encode_compact_bytes(raw_key)
            out += codec.encode_compact_bytes(value)
            n += 1
        path = self._staged_name(name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self._seal_staged(bytes(out)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._mu:
            self._staged[name] = path
            self._rewrites[name] = rewrite
        return {"file": name, "kvs": n, "backup_ts": backup_ts}

    def _staged_data(self, name: str, rewrite):
        """(data, effective_rewrite): staged bytes were rewritten at download
        time; a cold read re-applies the rewrite recorded then (an explicit
        caller rewrite wins — deliberate re-ingest under a new prefix)."""
        with self._mu:
            path = self._staged.get(name)
            recorded = self._rewrites.get(name)
        if path is not None and os.path.exists(path):
            with open(path, "rb") as f:
                return self._unseal_staged(f.read()), None
        if rewrite is None and recorded is not None:
            rewrite = recorded
        return self.storage.read(name), rewrite

    def cleanup(self, name: str) -> None:
        """Drop the staged bytes.  The rewrite rule recorded at download time
        is KEPT: a later re-restore of the same name must re-apply it on the
        cold re-read, never silently ingest un-rewritten keys."""
        with self._mu:
            path = self._staged.pop(name, None)
        if path is not None and os.path.exists(path):
            os.unlink(path)

    def forget(self, name: str) -> None:
        """Full removal, including the recorded rewrite rule."""
        self.cleanup(name)
        with self._mu:
            self._rewrites.pop(name, None)

    def staged_count(self) -> int:
        with self._mu:
            return len(self._staged)

    # -- mvcc entry construction ---------------------------------------------

    def _mvcc_entries(self, data, rewrite, restore_ts: int):
        """The committed-write representation of an import at restore_ts:
        (cf, key, value) entries, short values inlined in the write record."""
        entries: list[tuple[str, bytes, bytes]] = []
        n = 0
        for raw_key, value in self._iter_entries(data, rewrite):
            k = Key.from_raw(raw_key)
            if len(value) <= 255:
                w = Write(WriteType.PUT, restore_ts, short_value=value)
            else:
                w = Write(WriteType.PUT, restore_ts)
                entries.append((CF_DEFAULT, k.append_ts(restore_ts).encoded, value))
            entries.append((CF_WRITE, k.append_ts(restore_ts + 1).encoded, w.to_bytes()))
            n += 1
        return entries, n

    # -- ingest ----------------------------------------------------------------

    def restore(self, engine, name: str, restore_ts: int, ctx: dict | None = None,
                rewrite: tuple[bytes, bytes] | None = None) -> dict:
        """Engine-path ingest (local engines and RaftKv write path)."""
        data, rewrite = self._staged_data(name, rewrite)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        entries, n = self._mvcc_entries(data, rewrite, restore_ts)
        wb = WriteBatch()
        for cf, key, val in entries:
            wb.put_cf(cf, key, val)
        engine.write(ctx, wb)
        self.cleanup(name)
        return {"file": name, "kvs": n, "restored_at": restore_ts + 1}

    def ingest_via_raft(self, cluster_ingest, name: str, restore_ts: int,
                        rewrite: tuple[bytes, bytes] | None = None) -> dict:
        """Replicated ingest: hand the final entries to a raft ``ingest_sst``
        admin proposal (``cluster_ingest(payload_blob)``) so EVERY replica
        applies them from the log — the reference's IngestSst command shape."""
        data, rewrite = self._staged_data(name, rewrite)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        entries, n = self._mvcc_entries(data, rewrite, restore_ts)
        cluster_ingest(encode_ingest_entries(entries))
        self.cleanup(name)
        return {"file": name, "kvs": n, "restored_at": restore_ts + 1, "via": "raft"}

    def restore_via_sst(self, engine, name: str, restore_ts: int,
                        rewrite: tuple[bytes, bytes] | None = None,
                        workdir: str | None = None) -> dict:
        """Bulk restore straight into a NATIVE engine via SST ingest
        (sst_importer's real shape: build sorted immutable files, AddFile
        them) — one file copy + one WAL reference instead of N WAL records.
        Engine-local loads only; replicated restores use ingest_via_raft."""
        from ..native.engine import build_sst

        data, rewrite = self._staged_data(name, rewrite)
        if not data.startswith(MAGIC):
            raise ValueError(f"{name}: not a backup file")
        entries, n = self._mvcc_entries(data, rewrite, restore_ts)
        by_cf: dict[str, list[tuple[bytes, bytes]]] = {}
        for cf, key, val in entries:
            by_cf.setdefault(cf, []).append((key, val))
        sst_entries = []
        for cf in sorted(by_cf):
            sst_entries += [(cf, k, v) for k, v in sorted(by_cf[cf])]
        fd, path = tempfile.mkstemp(suffix=".sst", dir=workdir or self.workdir)
        os.close(fd)
        try:
            build_sst(path, sst_entries)
            engine.ingest_sst(path)
        finally:
            os.unlink(path)
        self.cleanup(name)
        return {"file": name, "kvs": n, "restored_at": restore_ts + 1, "via": "sst"}

    # -- duplicate detection ---------------------------------------------------

    def duplicate_detect(self, snapshot, name: str, min_commit_ts: int = 0,
                         rewrite: tuple[bytes, bytes] | None = None) -> list[dict]:
        """Keys the staged file would collide with: target keys that already
        hold a committed PUT/DELETE at commit_ts > min_commit_ts
        (src/import/duplicate_detect.rs DuplicateDetector semantics — the
        importer surfaces them instead of silently double-writing)."""
        data, rewrite = self._staged_data(name, rewrite)
        dups: list[dict] = []
        cur = snapshot.cursor_cf(CF_WRITE)  # one cursor; seeks reposition it
        for raw_key, _value in self._iter_entries(data, rewrite):
            k = Key.from_raw(raw_key)
            # newest committed version of this user key, if any
            if not cur.seek(k.append_ts(MAX_TS - 1).encoded):
                continue
            user, ts = split_ts(cur.key())
            if user != k.encoded:
                continue
            w = Write.from_bytes(cur.value())
            if w.write_type in (WriteType.PUT, WriteType.DELETE) and ts > min_commit_ts:
                dups.append({"key": raw_key, "commit_ts": ts, "type": w.write_type.name})
        return dups
