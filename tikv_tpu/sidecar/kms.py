"""KMS-backed master keys for encryption at rest.

Re-expression of ``components/cloud/src/kms.rs`` (the ``KmsProvider`` trait:
generate_data_key / decrypt_data_key) and
``components/encryption/src/master_key/kms.rs`` (KmsBackend): the master key
material lives IN the KMS — the store persists only the provider's opaque
``CiphertextBlob`` and asks the KMS to unwrap it at startup.  The AWS
implementation signs requests with the same SigV4 recipe as the S3 backend
(``cloud.py``), service name ``kms``, JSON protocol (X-Amz-Target headers).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import os
import time
import urllib.parse

from ..storage.encryption import MasterKey, seal, unseal
from .cloud import CloudError, _hmac_sha256, _sha256_hex


class KmsError(CloudError):
    pass


class KmsProvider:
    """cloud/src/kms.rs KmsProvider: wrap/unwrap 32-byte data-encryption
    keys.  ``generate_data_key`` returns (plaintext, ciphertext_blob);
    ``decrypt_data_key`` inverts the blob back to plaintext."""

    def generate_data_key(self) -> tuple[bytes, bytes]:
        raise NotImplementedError

    def decrypt_data_key(self, ciphertext: bytes) -> bytes:
        raise NotImplementedError


class AwsKms(KmsProvider):
    """AWS KMS over the JSON protocol with SigV4 (cloud/aws/src/kms.rs).

    Talks to any KMS-compatible endpoint (including the FakeKms test server),
    so zero-egress environments exercise the full signing + wire path."""

    def __init__(self, key_id: str, access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1", endpoint: str = "http://127.0.0.1:8800"):
        self.key_id = key_id
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        u = urllib.parse.urlparse(endpoint)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.https = u.scheme == "https"

    def _headers(self, target: str, payload: bytes) -> dict:
        t = time.gmtime(time.time())
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
        datestamp = time.strftime("%Y%m%d", t)
        payload_hash = _sha256_hex(payload)
        host = f"{self.host}:{self.port}"
        headers = {
            "content-type": "application/x-amz-json-1.1",
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
            "x-amz-target": target,
        }
        signed = ";".join(sorted(headers))
        canonical = "\n".join([
            "POST", "/", "",
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed, payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/kms/aws4_request"
        to_sign = "\n".join(
            ["AWS4-HMAC-SHA256", amz_date, scope, _sha256_hex(canonical.encode())])
        k = _hmac_sha256(b"AWS4" + self.secret_key.encode(), datestamp)
        k = _hmac_sha256(k, self.region)
        k = _hmac_sha256(k, "kms")
        k = _hmac_sha256(k, "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        return headers

    def _call(self, target: str, body: dict) -> dict:
        payload = json.dumps(body).encode()
        cls = http.client.HTTPSConnection if self.https else http.client.HTTPConnection
        conn = cls(self.host, self.port, timeout=30)
        try:
            conn.request("POST", "/", body=payload,
                         headers=self._headers(target, payload))
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise KmsError(f"KMS {target} failed: {resp.status} {raw[:200]!r}")
            return json.loads(raw)
        finally:
            conn.close()

    def generate_data_key(self) -> tuple[bytes, bytes]:
        r = self._call("TrentService.GenerateDataKey",
                       {"KeyId": self.key_id, "KeySpec": "AES_256"})
        return (base64.b64decode(r["Plaintext"]),
                base64.b64decode(r["CiphertextBlob"]))

    def decrypt_data_key(self, ciphertext: bytes) -> bytes:
        r = self._call("TrentService.Decrypt",
                       {"KeyId": self.key_id,
                        "CiphertextBlob": base64.b64encode(ciphertext).decode()})
        return base64.b64decode(r["Plaintext"])


class KmsMasterKey(MasterKey):
    """master_key/kms.rs KmsBackend: a MasterKey whose material came from the
    KMS; ``ciphertext`` is the only thing worth persisting."""

    def __init__(self, plaintext: bytes, ciphertext: bytes):
        super().__init__(plaintext)
        self.ciphertext = ciphertext

    @classmethod
    def open(cls, provider: KmsProvider, state_path: str) -> "KmsMasterKey":
        """Load-or-create: an existing wrapped blob at ``state_path`` is
        unwrapped by the KMS; otherwise a fresh data key is generated and
        its ciphertext persisted (atomic tmp+rename, like the key dict)."""
        if os.path.exists(state_path):
            with open(state_path, "rb") as f:
                ct = f.read()
            return cls(provider.decrypt_data_key(ct), ct)
        pt, ct = provider.generate_data_key()
        tmp = state_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(ct)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, state_path)
        return cls(pt, ct)


class FakeKms:
    """In-process KMS endpoint for tests (the reference tests against a
    fake AWS credential provider the same way): implements GenerateDataKey /
    Decrypt over the JSON protocol, wrapping plaintext under a local secret,
    and rejects requests without a SigV4 Authorization header."""

    def __init__(self, key_id: str = "test-key", host: str = "127.0.0.1"):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.key_id = key_id
        self._secret = os.urandom(32)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                target = self.headers.get("X-Amz-Target", "")
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256"):
                    self._reply(403, {"__type": "AccessDeniedException"})
                    return
                if body.get("KeyId") != outer.key_id:
                    self._reply(400, {"__type": "NotFoundException"})
                    return
                if target.endswith("GenerateDataKey"):
                    pt = os.urandom(32)
                    ct = seal(outer._secret, pt)
                    self._reply(200, {
                        "Plaintext": base64.b64encode(pt).decode(),
                        "CiphertextBlob": base64.b64encode(ct).decode(),
                        "KeyId": outer.key_id,
                    })
                elif target.endswith("Decrypt"):
                    try:
                        pt = unseal(outer._secret,
                                    base64.b64decode(body["CiphertextBlob"]))
                    except (KeyError, ValueError):
                        self._reply(400, {"__type": "InvalidCiphertextException"})
                        return
                    self._reply(200, {
                        "Plaintext": base64.b64encode(pt).decode(),
                        "KeyId": outer.key_id,
                    })
                else:
                    self._reply(400, {"__type": "UnknownOperationException"})

            def _reply(self, code: int, obj: dict):
                raw = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/x-amz-json-1.1")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self._srv = ThreadingHTTPServer((host, 0), Handler)
        self.addr = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://{self.addr[0]}:{self.addr[1]}"

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
