"""tipb message definitions (pingcap/tipb contract, proto2).

Covers the coprocessor surface the reference serves: DAGRequest and its
executor tree (executor.proto), expressions (expression.proto), schema
(schema.proto), and SelectResponse/Chunk/StreamResponse (select.proto), plus
the checksum protocol (checksum.proto).

Field numbers and enum values are reconstructed from the public pingcap/tipb
protos the reference pins (Cargo.toml:219).  The sandbox has no copy of the
.proto sources (git dependency, zero egress), so numbering fidelity is
best-effort-documented rather than machine-verified; the differential tests
in tests/test_proto_wire.py compile the reconstruction with protoc and assert
this codec is byte-identical to the real protobuf runtime over it.
"""

from __future__ import annotations

from .wire import (
    Field as F,
    K_BOOL,
    K_BYTES,
    K_DOUBLE,
    K_INT,
    K_MSG,
    K_STR,
    PbMessage,
)


class Tipb(PbMessage):
    SYNTAX = 2


# ---------------------------------------------------------------------------
# expression.proto
# ---------------------------------------------------------------------------

class ExprType:
    """Constant/aggregate expression tags (tipb expression.proto ExprType)."""

    Null = 0
    Int64 = 1
    Uint64 = 2
    Float32 = 3
    Float64 = 4
    String = 5
    Bytes = 6
    MysqlBit = 101
    MysqlDecimal = 102
    MysqlDuration = 103
    MysqlEnum = 104
    MysqlHex = 105
    MysqlSet = 106
    MysqlTime = 107
    MysqlJson = 108
    ValueList = 151
    ColumnRef = 201
    # aggregate functions
    Count = 3001
    Sum = 3002
    Avg = 3003
    Min = 3004
    Max = 3005
    First = 3006
    GroupConcat = 3007
    AggBitAnd = 3008
    AggBitOr = 3009
    AggBitXor = 3010
    Std = 3011
    Stddev = 3012
    StddevPop = 3013
    StddevSamp = 3014
    VarPop = 3015
    VarSamp = 3016
    Variance = 3017
    JsonArrayAgg = 3018
    JsonObjectAgg = 3019
    ApproxCountDistinct = 3020
    ScalarFunc = 10000


class FieldTypePb(Tipb):
    FIELDS = (
        F(1, "tp", K_INT),
        F(2, "flag", K_INT, signed=False),
        F(3, "flen", K_INT),
        F(4, "decimal", K_INT),
        F(5, "collate", K_INT),
        F(6, "charset", K_STR),
        F(7, "elems", K_STR, repeated=True),
    )


class Expr(Tipb):
    FIELDS = (
        F(1, "tp", K_INT),
        F(2, "val", K_BYTES),
        F(3, "children", K_MSG, repeated=True, msg_type=lambda: Expr),
        F(4, "sig", K_INT),
        F(5, "field_type", K_MSG, msg_type=lambda: FieldTypePb),
        F(6, "has_distinct", K_BOOL),
    )


class ByItem(Tipb):
    FIELDS = (
        F(1, "expr", K_MSG, msg_type=lambda: Expr),
        F(2, "desc", K_BOOL),
    )


# ---------------------------------------------------------------------------
# schema.proto
# ---------------------------------------------------------------------------

class ColumnInfoPb(Tipb):
    FIELDS = (
        F(1, "column_id", K_INT),
        F(2, "tp", K_INT),
        F(3, "collation", K_INT),
        F(4, "column_len", K_INT),
        F(5, "decimal", K_INT),
        F(6, "flag", K_INT),
        F(7, "elems", K_STR, repeated=True),
        F(8, "default_val", K_BYTES),
        F(21, "pk_handle", K_BOOL),
    )


class TableInfoPb(Tipb):
    FIELDS = (
        F(1, "table_id", K_INT),
        F(2, "columns", K_MSG, repeated=True, msg_type=lambda: ColumnInfoPb),
    )


class IndexInfoPb(Tipb):
    FIELDS = (
        F(1, "table_id", K_INT),
        F(2, "index_id", K_INT),
        F(3, "columns", K_MSG, repeated=True, msg_type=lambda: ColumnInfoPb),
        F(4, "unique", K_BOOL),
    )


class KeyRangePb(Tipb):
    """tipb KeyRange (low/high) — distinct from coprocessor.KeyRange."""

    FIELDS = (
        F(1, "low", K_BYTES),
        F(2, "high", K_BYTES),
    )


# ---------------------------------------------------------------------------
# executor.proto
# ---------------------------------------------------------------------------

class ExecType:
    TypeTableScan = 0
    TypeIndexScan = 1
    TypeSelection = 2
    TypeAggregation = 3  # hash aggregation
    TypeTopN = 4
    TypeLimit = 5
    TypeStreamAgg = 6


class TableScanPb(Tipb):
    FIELDS = (
        F(1, "table_id", K_INT),
        F(2, "columns", K_MSG, repeated=True, msg_type=lambda: ColumnInfoPb),
        F(3, "desc", K_BOOL),
        F(4, "primary_column_ids", K_INT, repeated=True),
    )


class IndexScanPb(Tipb):
    FIELDS = (
        F(1, "table_id", K_INT),
        F(2, "index_id", K_INT),
        F(3, "columns", K_MSG, repeated=True, msg_type=lambda: ColumnInfoPb),
        F(4, "desc", K_BOOL),
        F(5, "unique", K_BOOL),
    )


class SelectionPb(Tipb):
    FIELDS = (
        F(1, "conditions", K_MSG, repeated=True, msg_type=lambda: Expr),
    )


class AggregationPb(Tipb):
    FIELDS = (
        F(1, "group_by", K_MSG, repeated=True, msg_type=lambda: Expr),
        F(2, "agg_func", K_MSG, repeated=True, msg_type=lambda: Expr),
        F(3, "streamed", K_BOOL),
    )


class TopNPb(Tipb):
    FIELDS = (
        F(1, "order_by", K_MSG, repeated=True, msg_type=lambda: ByItem),
        F(2, "limit", K_INT),
    )


class LimitPb(Tipb):
    FIELDS = (
        F(1, "limit", K_INT, signed=False),
    )


class ExecutorPb(Tipb):
    FIELDS = (
        F(1, "tp", K_INT),
        F(2, "tbl_scan", K_MSG, msg_type=lambda: TableScanPb),
        F(3, "idx_scan", K_MSG, msg_type=lambda: IndexScanPb),
        F(4, "selection", K_MSG, msg_type=lambda: SelectionPb),
        F(5, "aggregation", K_MSG, msg_type=lambda: AggregationPb),
        F(6, "top_n", K_MSG, msg_type=lambda: TopNPb),
        F(7, "limit", K_MSG, msg_type=lambda: LimitPb),
        F(10, "executor_id", K_STR),
    )


class ExecutorExecutionSummary(Tipb):
    FIELDS = (
        F(1, "time_processed_ns", K_INT, signed=False),
        F(2, "num_produced_rows", K_INT, signed=False),
        F(3, "num_iterations", K_INT, signed=False),
        F(4, "executor_id", K_STR),
        F(5, "concurrency", K_INT, signed=False),
    )


# ---------------------------------------------------------------------------
# select.proto
# ---------------------------------------------------------------------------

class EncodeType:
    TypeDefault = 0  # datum-encoded rows in Chunk.rows_data
    TypeChunk = 1    # Arrow-like column chunks (chunk/column.rs layout)


class DAGRequest(Tipb):
    FIELDS = (
        F(1, "start_ts_fallback", K_INT, signed=False),
        F(2, "executors", K_MSG, repeated=True, msg_type=lambda: ExecutorPb),
        F(3, "time_zone_offset", K_INT),
        F(4, "flags", K_INT, signed=False),
        F(5, "output_offsets", K_INT, repeated=True, signed=False),
        F(6, "collect_range_counts", K_BOOL),
        F(7, "max_warning_count", K_INT, signed=False),
        F(8, "encode_type", K_INT),
        F(9, "sql_mode", K_INT, signed=False),
        F(11, "time_zone_name", K_STR),
        F(12, "collect_execution_summaries", K_BOOL),
        F(13, "max_allowed_packet", K_INT, signed=False),
        F(15, "is_rpn_expr", K_BOOL),
    )


class ErrorPb(Tipb):
    FIELDS = (
        F(1, "code", K_INT),
        F(2, "msg", K_STR),
    )


class RowMeta(Tipb):
    FIELDS = (
        F(1, "handle", K_INT),
        F(2, "length", K_INT),
    )


class ChunkPb(Tipb):
    FIELDS = (
        F(3, "rows_data", K_BYTES),
        F(4, "rows_meta", K_MSG, repeated=True, msg_type=lambda: RowMeta),
    )


class SelectResponse(Tipb):
    FIELDS = (
        F(1, "error", K_MSG, msg_type=lambda: ErrorPb),
        F(3, "chunks", K_MSG, repeated=True, msg_type=lambda: ChunkPb),
        F(4, "warnings", K_MSG, repeated=True, msg_type=lambda: ErrorPb),
        F(5, "output_counts", K_INT, repeated=True),
        F(6, "warning_count", K_INT),
        F(8, "execution_summaries", K_MSG, repeated=True,
          msg_type=lambda: ExecutorExecutionSummary),
        F(9, "encode_type", K_INT),
    )


class StreamResponse(Tipb):
    FIELDS = (
        F(1, "error", K_MSG, msg_type=lambda: ErrorPb),
        F(3, "data", K_BYTES),
        F(4, "warnings", K_MSG, repeated=True, msg_type=lambda: ErrorPb),
        F(5, "output_counts", K_INT, repeated=True),
        F(6, "warning_count", K_INT),
    )


# ---------------------------------------------------------------------------
# checksum.proto
# ---------------------------------------------------------------------------

class ChecksumScanOn:
    Table = 0
    Index = 1


class ChecksumRequest(Tipb):
    FIELDS = (
        F(1, "start_ts_fallback", K_INT, signed=False),
        F(2, "scan_on", K_INT),
        F(3, "algorithm", K_INT),
    )


class ChecksumResponse(Tipb):
    FIELDS = (
        F(1, "checksum", K_INT, signed=False),
        F(2, "total_kvs", K_INT, signed=False),
        F(3, "total_bytes", K_INT, signed=False),
    )


# ---------------------------------------------------------------------------
# analyze.proto (column/index stats collection)
# ---------------------------------------------------------------------------

class AnalyzeType:
    TypeIndex = 0
    TypeColumn = 1


class AnalyzeColumnsReq(Tipb):
    FIELDS = (
        F(1, "bucket_size", K_INT),
        F(2, "sample_size", K_INT),
        F(3, "sketch_size", K_INT),
        F(4, "columns_info", K_MSG, repeated=True, msg_type=lambda: ColumnInfoPb),
        F(5, "cmsketch_depth", K_INT),
        F(6, "cmsketch_width", K_INT),
    )


class AnalyzeIndexReq(Tipb):
    FIELDS = (
        F(1, "bucket_size", K_INT),
        F(2, "num_columns", K_INT),
        F(3, "cmsketch_depth", K_INT),
        F(4, "cmsketch_width", K_INT),
    )


class AnalyzeReq(Tipb):
    FIELDS = (
        F(1, "tp", K_INT),
        F(2, "start_ts_fallback", K_INT, signed=False),
        F(3, "flags", K_INT, signed=False),
        F(4, "time_zone_offset", K_INT),
        F(5, "idx_req", K_MSG, msg_type=lambda: AnalyzeIndexReq),
        F(6, "col_req", K_MSG, msg_type=lambda: AnalyzeColumnsReq),
    )


# ---------------------------------------------------------------------------
# ScalarFuncSig numbering
# ---------------------------------------------------------------------------

def _sig_block(base: int, names: list[str]) -> dict[str, int]:
    return {name: base + i for i, name in enumerate(names)}


_TYPE_SUFFIXES = ["Int", "Real", "Decimal", "String", "Time", "Duration", "Json"]

#: Reconstructed tipb ScalarFuncSig values for the signatures this
#: coprocessor implements (CATALOG.md).  Layout follows the public proto's
#: block structure: casts 0-66 (stride 10 per source type), comparisons
#: 100-166 (stride 10 per operator), arithmetic 200+, and the sparse blocks
#: above 2000.
SCALAR_FUNC_SIG: dict[str, int] = {}
for _i, _src in enumerate(_TYPE_SUFFIXES):
    SCALAR_FUNC_SIG.update(_sig_block(_i * 10, [f"Cast{_src}As{_dst}" for _dst in _TYPE_SUFFIXES]))
for _i, _op in enumerate(["Lt", "Le", "Gt", "Ge", "Eq", "Ne", "NullEq"]):
    SCALAR_FUNC_SIG.update(
        {f"{_op}{_t}": 100 + _i * 10 + _j for _j, _t in enumerate(_TYPE_SUFFIXES)})
SCALAR_FUNC_SIG.update({
    "PlusReal": 200, "PlusDecimal": 201, "PlusInt": 203,
    "MinusReal": 204, "MinusDecimal": 205, "MinusInt": 207,
    "MultiplyReal": 208, "MultiplyDecimal": 209, "MultiplyInt": 210,
    "DivideReal": 211, "DivideDecimal": 212,
    "IntDivideInt": 213, "IntDivideDecimal": 214,
    "ModReal": 215, "ModDecimal": 216, "ModInt": 217,
    "MultiplyIntUnsigned": 218,
    "AbsInt": 2101, "AbsUInt": 2102, "AbsReal": 2103, "AbsDecimal": 2104,
    "CeilIntToDec": 2105, "CeilIntToInt": 2106, "CeilDecToIntOverflow": 2107,
    "CeilDecToDec": 2108, "CeilReal": 2109,
    "FloorIntToDec": 2110, "FloorIntToInt": 2111, "FloorDecToIntOverflow": 2112,
    "FloorDecToDec": 2113, "FloorReal": 2114,
    "RoundReal": 2121, "RoundInt": 2122, "RoundDec": 2123,
    "RoundWithFracReal": 2124, "RoundWithFracInt": 2125, "RoundWithFracDec": 2126,
    "Log1Arg": 2131, "Log2Args": 2132, "Log2": 2133, "Log10": 2134,
    "Rand": 2135, "RandWithSeedFirstGen": 2136,
    "Pow": 2137, "Conv": 2138, "CRC32": 2139, "Sign": 2140,
    "Sqrt": 2141, "Acos": 2142, "Asin": 2143, "Atan1Arg": 2144,
    "Atan2Args": 2145, "Cos": 2146, "Cot": 2147, "Degrees": 2148,
    "Exp": 2149, "PI": 2150, "Radians": 2151, "Sin": 2152, "Tan": 2153,
    "TruncateInt": 2154, "TruncateReal": 2155, "TruncateDecimal": 2156,
    "TruncateUint": 2157,
    "LogicalAnd": 3101, "LogicalOr": 3102, "LogicalXor": 3103,
    "UnaryNotDecimal": 3104, "UnaryNotInt": 3105, "UnaryNotReal": 3106,
    "UnaryMinusInt": 3108, "UnaryMinusReal": 3109, "UnaryMinusDecimal": 3110,
    "DecimalIsNull": 3111, "DurationIsNull": 3112, "RealIsNull": 3113,
    "StringIsNull": 3114, "TimeIsNull": 3115, "IntIsNull": 3116,
    "JsonIsNull": 3117,
    "BitAndSig": 3118, "BitOrSig": 3119, "BitXorSig": 3120, "BitNegSig": 3121,
    "IntIsTrue": 3122, "RealIsTrue": 3123, "DecimalIsTrue": 3124,
    "IntIsFalse": 3125, "RealIsFalse": 3126, "DecimalIsFalse": 3127,
    "LeftShift": 3129, "RightShift": 3130,
    "InInt": 4001, "InReal": 4002, "InDecimal": 4003, "InString": 4004,
    "InTime": 4005, "InDuration": 4006, "InJson": 4007,
    "IfNullInt": 4101, "IfNullReal": 4102, "IfNullDecimal": 4103,
    "IfNullString": 4104, "IfNullTime": 4105, "IfNullDuration": 4106,
    "IfInt": 4107, "IfReal": 4108, "IfDecimal": 4109, "IfString": 4110,
    "IfTime": 4111, "IfDuration": 4112, "IfNullJson": 4113, "IfJson": 4114,
    "CaseWhenInt": 4208, "CaseWhenReal": 4209, "CaseWhenDecimal": 4210,
    "CaseWhenString": 4211, "CaseWhenTime": 4212, "CaseWhenDuration": 4213,
    "CaseWhenJson": 4214,
    "LikeSig": 4310, "RegexpSig": 4311, "RegexpUTF8Sig": 4312,
    "JsonExtractSig": 5006, "JsonSetSig": 5007, "JsonInsertSig": 5008,
    "JsonReplaceSig": 5009, "JsonRemoveSig": 5010, "JsonMergeSig": 5011,
    "JsonObjectSig": 5012, "JsonArraySig": 5013, "JsonValidJsonSig": 5014,
    "JsonContainsSig": 5015, "JsonArrayAppendSig": 5016,
    "JsonValidStringSig": 5017, "JsonValidOthersSig": 5018,
    "JsonTypeSig": 5023, "JsonQuoteSig": 5024, "JsonUnquoteSig": 5025,
    "JsonDepthSig": 5028, "JsonLengthSig": 5027, "JsonKeysSig": 5029,
    "JsonKeys2ArgsSig": 5031, "JsonContainsPathSig": 5032,
    "CoalesceInt": 4201, "CoalesceReal": 4202, "CoalesceDecimal": 4203,
    "CoalesceString": 4204, "CoalesceTime": 4205, "CoalesceDuration": 4206,
    "CoalesceJson": 4207,
    "GreatestInt": 4215, "GreatestReal": 4216, "GreatestDecimal": 4217,
    "GreatestString": 4218, "GreatestTime": 4219,
    "LeastInt": 4220, "LeastReal": 4221, "LeastDecimal": 4222,
    "LeastString": 4223, "LeastTime": 4224,
    "IntervalInt": 4225, "IntervalReal": 4226,
})
SIG_NAME = {v: k for k, v in SCALAR_FUNC_SIG.items()}
