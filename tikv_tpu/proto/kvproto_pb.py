"""kvproto message definitions (pingcap/kvproto contract, proto3).

The request/response pairs for every handler the reference's gRPC service
exposes in src/server/service/kv.rs:129-303: txn KV (get/scan/prewrite/
commit/…), raw KV, coprocessor, and the shared metapb/errorpb submessages.

Field numbers are reconstructed from the public pingcap/kvproto protos the
reference pins (Cargo.toml:165); see tipb_pb.py's docstring for the fidelity
caveat and tests/test_proto_wire.py for the protoc differential tests.
"""

from __future__ import annotations

from .wire import (
    Field as F,
    K_BOOL,
    K_BYTES,
    K_INT,
    K_MSG,
    K_STR,
    PbMessage,
)


class Kv(PbMessage):
    SYNTAX = 3


def U(n, name, **kw):
    return F(n, name, K_INT, signed=False, **kw)


def I64(n, name, **kw):
    return F(n, name, K_INT, **kw)


def B(n, name, **kw):
    return F(n, name, K_BOOL, **kw)


def Y(n, name, **kw):
    return F(n, name, K_BYTES, **kw)


def S(n, name, **kw):
    return F(n, name, K_STR, **kw)


def M(n, name, mt, **kw):
    return F(n, name, K_MSG, msg_type=mt, **kw)


# ---------------------------------------------------------------------------
# metapb.proto
# ---------------------------------------------------------------------------

class PeerRole:
    Voter = 0
    Learner = 1
    IncomingVoter = 2
    DemotingVoter = 3


class RegionEpoch(Kv):
    FIELDS = (U(1, "conf_ver"), U(2, "version"))


class Peer(Kv):
    FIELDS = (U(1, "id"), U(2, "store_id"), U(3, "role"))


class Region(Kv):
    FIELDS = (
        U(1, "id"),
        Y(2, "start_key"),
        Y(3, "end_key"),
        M(4, "region_epoch", lambda: RegionEpoch),
        M(5, "peers", lambda: Peer, repeated=True),
    )


class Store(Kv):
    FIELDS = (
        U(1, "id"),
        S(2, "address"),
        U(3, "state"),
        S(21, "status_address"),
    )


# ---------------------------------------------------------------------------
# errorpb.proto
# ---------------------------------------------------------------------------

class NotLeader(Kv):
    FIELDS = (U(1, "region_id"), M(2, "leader", lambda: Peer))


class RegionNotFound(Kv):
    FIELDS = (U(1, "region_id"),)


class KeyNotInRegion(Kv):
    FIELDS = (Y(1, "key"), U(2, "region_id"), Y(3, "start_key"), Y(4, "end_key"))


class EpochNotMatch(Kv):
    FIELDS = (M(1, "current_regions", lambda: Region, repeated=True),)


class ServerIsBusy(Kv):
    FIELDS = (S(1, "reason"), U(2, "backoff_ms"))


class StaleCommand(Kv):
    FIELDS = ()


class StoreNotMatch(Kv):
    FIELDS = (U(1, "request_store_id"), U(2, "actual_store_id"))


class RaftEntryTooLarge(Kv):
    FIELDS = (U(1, "region_id"), U(2, "entry_size"))


class DataIsNotReady(Kv):
    """errorpb.DataIsNotReady: a follower stale read above the region's
    resolved-ts watermark (docs/stale_reads.md); safe_ts tells the client
    the highest ts this replica CAN serve."""

    FIELDS = (U(1, "region_id"), U(2, "peer_id"), U(3, "safe_ts"))


class RegionError(Kv):
    """errorpb.Error."""

    FIELDS = (
        S(1, "message"),
        M(2, "not_leader", lambda: NotLeader),
        M(3, "region_not_found", lambda: RegionNotFound),
        M(4, "key_not_in_region", lambda: KeyNotInRegion),
        M(5, "epoch_not_match", lambda: EpochNotMatch),
        M(6, "server_is_busy", lambda: ServerIsBusy),
        M(7, "stale_command", lambda: StaleCommand),
        M(8, "store_not_match", lambda: StoreNotMatch),
        M(9, "raft_entry_too_large", lambda: RaftEntryTooLarge),
        M(13, "data_is_not_ready", lambda: DataIsNotReady),
    )


# ---------------------------------------------------------------------------
# kvrpcpb.proto — shared
# ---------------------------------------------------------------------------

class CommandPri:
    Normal = 0
    Low = 1
    High = 2


class IsolationLevel:
    SI = 0
    RC = 1


class Op:
    Put = 0
    Del = 1
    Lock = 2
    Rollback = 3
    PessimisticLock = 4
    CheckNotExists = 5


class Action:
    NoAction = 0
    TTLExpireRollback = 1
    LockNotExistRollback = 2
    MinCommitTSPushed = 3
    LockNotExistDoNothing = 4


class Context(Kv):
    FIELDS = (
        U(1, "region_id"),
        M(2, "region_epoch", lambda: RegionEpoch),
        M(3, "peer", lambda: Peer),
        U(5, "term"),
        U(6, "priority"),
        U(7, "isolation_level"),
        B(8, "not_fill_cache"),
        B(9, "sync_log"),
        B(10, "record_time_stat"),
        B(11, "record_scan_stat"),
        B(12, "replica_read"),
        U(13, "resolved_locks", repeated=True, packed=True),
        U(14, "max_execution_duration_ms"),
        U(15, "applied_index"),
        U(16, "task_id"),
        B(17, "stale_read"),
    )


class LockInfo(Kv):
    FIELDS = (
        Y(1, "primary_lock"),
        U(2, "lock_version"),
        Y(3, "key"),
        U(4, "lock_ttl"),
        U(5, "txn_size"),
        U(6, "lock_type"),
        U(7, "lock_for_update_ts"),
        B(8, "use_async_commit"),
        U(9, "min_commit_ts"),
        Y(10, "secondaries", repeated=True),
    )


class WriteConflict(Kv):
    FIELDS = (
        U(1, "start_ts"),
        U(2, "conflict_ts"),
        Y(3, "key"),
        Y(4, "primary"),
        U(5, "conflict_commit_ts"),
    )


class AlreadyExist(Kv):
    FIELDS = (Y(1, "key"),)


class Deadlock(Kv):
    FIELDS = (U(1, "lock_ts"), Y(2, "lock_key"), U(3, "deadlock_key_hash"))


class CommitTsExpired(Kv):
    FIELDS = (U(1, "start_ts"), U(2, "attempted_commit_ts"), Y(3, "key"),
              U(4, "min_commit_ts"))


class TxnNotFound(Kv):
    FIELDS = (U(1, "start_ts"), Y(2, "primary_key"))


class CommitTsTooLarge(Kv):
    FIELDS = (U(1, "commit_ts"),)


class KeyError(Kv):
    FIELDS = (
        M(1, "locked", lambda: LockInfo),
        S(2, "retryable"),
        S(3, "abort"),
        M(4, "conflict", lambda: WriteConflict),
        M(5, "already_exist", lambda: AlreadyExist),
        M(6, "deadlock", lambda: Deadlock),
        M(7, "commit_ts_expired", lambda: CommitTsExpired),
        M(8, "txn_not_found", lambda: TxnNotFound),
        M(9, "commit_ts_too_large", lambda: CommitTsTooLarge),
    )


class KvPair(Kv):
    FIELDS = (M(1, "error", lambda: KeyError), Y(2, "key"), Y(3, "value"))


class Mutation(Kv):
    FIELDS = (U(1, "op"), Y(2, "key"), Y(3, "value"), U(4, "assertion"))


class TimeDetail(Kv):
    FIELDS = (I64(1, "wait_wall_time_ms"), I64(2, "process_wall_time_ms"),
              I64(3, "total_rpc_wall_time_ns"))


class ScanInfo(Kv):
    FIELDS = (I64(1, "total"), I64(2, "processed"), I64(3, "read_bytes"))


class ScanDetail(Kv):
    FIELDS = (M(1, "write", lambda: ScanInfo), M(2, "lock", lambda: ScanInfo),
              M(3, "data", lambda: ScanInfo))


class ScanDetailV2(Kv):
    FIELDS = (
        U(1, "processed_versions"),
        U(2, "total_versions"),
        U(3, "rocksdb_delete_skipped_count"),
        U(4, "rocksdb_key_skipped_count"),
        U(5, "rocksdb_block_cache_hit_count"),
        U(6, "rocksdb_block_read_count"),
        U(7, "rocksdb_block_read_byte"),
        U(8, "processed_versions_size"),
    )


class ExecDetails(Kv):
    FIELDS = (M(1, "time_detail", lambda: TimeDetail),
              M(2, "scan_detail", lambda: ScanDetail))


class ExecDetailsV2(Kv):
    FIELDS = (M(1, "time_detail", lambda: TimeDetail),
              M(2, "scan_detail_v2", lambda: ScanDetailV2))


# ---------------------------------------------------------------------------
# kvrpcpb.proto — txn KV request/response pairs (kv.rs:159-240)
# ---------------------------------------------------------------------------

class GetRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"), U(3, "version"))


class GetResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "error", lambda: KeyError),
        Y(3, "value"),
        B(4, "not_found"),
        M(6, "exec_details_v2", lambda: ExecDetailsV2),
    )


class ScanRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        Y(2, "start_key"),
        U(3, "limit"),
        U(4, "version"),
        B(5, "key_only"),
        B(6, "reverse"),
        Y(7, "end_key"),
        U(8, "sample_step"),
    )


class ScanResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "pairs", lambda: KvPair, repeated=True),
        M(3, "error", lambda: KeyError),
    )


class PrewriteRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        M(2, "mutations", lambda: Mutation, repeated=True),
        Y(3, "primary_lock"),
        U(4, "start_version"),
        U(5, "lock_ttl"),
        B(6, "skip_constraint_check"),
        B(7, "is_pessimistic_lock", repeated=True, packed=True),
        U(8, "txn_size"),
        U(9, "for_update_ts"),
        U(10, "min_commit_ts"),
        B(11, "use_async_commit"),
        Y(12, "secondaries", repeated=True),
        B(13, "try_one_pc"),
        U(14, "max_commit_ts"),
    )


class PrewriteResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "errors", lambda: KeyError, repeated=True),
        U(3, "min_commit_ts"),
        U(4, "one_pc_commit_ts"),
    )


class CommitRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        U(2, "start_version"),
        Y(3, "keys", repeated=True),
        U(4, "commit_version"),
    )


class CommitResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "error", lambda: KeyError),
        U(3, "commit_version"),
    )


class BatchGetRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "keys", repeated=True),
              U(3, "version"))


class BatchGetResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "pairs", lambda: KvPair, repeated=True),
        M(4, "error", lambda: KeyError),
    )


class BatchRollbackRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), U(2, "start_version"),
              Y(3, "keys", repeated=True))


class BatchRollbackResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "error", lambda: KeyError))


class CleanupRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"),
              U(3, "start_version"), U(4, "current_ts"))


class CleanupResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "error", lambda: KeyError), U(3, "commit_version"))


class ScanLockRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        U(2, "max_version"),
        Y(3, "start_key"),
        U(4, "limit"),
        Y(5, "end_key"),
    )


class ScanLockResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "error", lambda: KeyError),
        M(3, "locks", lambda: LockInfo, repeated=True),
    )


class TxnInfo(Kv):
    FIELDS = (U(1, "txn"), U(2, "status"))


class ResolveLockRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        U(2, "start_version"),
        U(3, "commit_version"),
        M(4, "txn_infos", lambda: TxnInfo, repeated=True),
        Y(5, "keys", repeated=True),
    )


class ResolveLockResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "error", lambda: KeyError))


class TxnHeartBeatRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "primary_lock"),
              U(3, "start_version"), U(4, "advise_lock_ttl"))


class TxnHeartBeatResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "error", lambda: KeyError), U(3, "lock_ttl"))


class CheckTxnStatusRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        Y(2, "primary_key"),
        U(3, "lock_ts"),
        U(4, "caller_start_ts"),
        U(5, "current_ts"),
        B(6, "rollback_if_not_exist"),
        B(7, "force_sync_commit"),
        B(8, "resolving_pessimistic_lock"),
    )


class CheckTxnStatusResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "error", lambda: KeyError),
        U(3, "lock_ttl"),
        U(4, "commit_version"),
        U(5, "action"),
        M(6, "lock_info", lambda: LockInfo),
    )


class CheckSecondaryLocksRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "keys", repeated=True),
              U(3, "start_version"))


class CheckSecondaryLocksResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "error", lambda: KeyError),
        M(3, "locks", lambda: LockInfo, repeated=True),
        U(4, "commit_ts"),
    )


class PessimisticLockRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        M(2, "mutations", lambda: Mutation, repeated=True),
        Y(3, "primary_lock"),
        U(4, "start_version"),
        U(5, "lock_ttl"),
        U(6, "for_update_ts"),
        B(7, "is_first_lock"),
        I64(8, "wait_timeout"),
        B(9, "force"),
        B(10, "return_values"),
        U(11, "min_commit_ts"),
        B(12, "check_existence"),
    )


class PessimisticLockResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        M(2, "errors", lambda: KeyError, repeated=True),
        U(3, "commit_ts"),
        Y(4, "values", repeated=True),
        B(5, "not_founds", repeated=True, packed=True),
    )


class PessimisticRollbackRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), U(2, "start_version"),
              U(3, "for_update_ts"), Y(4, "keys", repeated=True))


class PessimisticRollbackResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "errors", lambda: KeyError, repeated=True))


class DeleteRangeRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "start_key"),
              Y(3, "end_key"), B(4, "notify_only"))


class DeleteRangeResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"))


class GCRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), U(2, "safe_point"))


class GCResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "error", lambda: KeyError))


# ---------------------------------------------------------------------------
# kvrpcpb.proto — raw KV
# ---------------------------------------------------------------------------

class RawGetRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"), S(3, "cf"))


class RawGetResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"),
              Y(3, "value"), B(4, "not_found"))


class RawPutRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"), Y(3, "value"),
              S(4, "cf"), U(5, "ttl"), B(6, "for_cas"))


class RawPutResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"))


class RawDeleteRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"), S(3, "cf"),
              B(4, "for_cas"))


class RawDeleteResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"))


class RawScanRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        Y(2, "start_key"),
        U(3, "limit"),
        B(4, "key_only"),
        S(5, "cf"),
        B(6, "reverse"),
        Y(7, "end_key"),
    )


class RawScanResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "kvs", lambda: KvPair, repeated=True))


class RawBatchGetRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "keys", repeated=True),
              S(3, "cf"))


class RawBatchGetResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError),
              M(2, "pairs", lambda: KvPair, repeated=True))


class RawBatchPutRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        M(2, "pairs", lambda: KvPair, repeated=True),
        S(3, "cf"),
        U(4, "ttl"),
        B(5, "for_cas"),
        U(6, "ttls", repeated=True, packed=True),
    )


class RawBatchPutResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"))


class RawBatchDeleteRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "keys", repeated=True),
              S(3, "cf"), B(4, "for_cas"))


class RawBatchDeleteResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"))


class RawDeleteRangeRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "start_key"),
              Y(3, "end_key"), S(4, "cf"))


class RawDeleteRangeResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"))


class RawCasRequest(Kv):
    FIELDS = (
        M(1, "context", lambda: Context),
        Y(2, "key"),
        Y(3, "value"),
        B(4, "previous_not_exist"),
        Y(5, "previous_value"),
        S(6, "cf"),
        U(7, "ttl"),
    )


class RawCasResponse(Kv):
    FIELDS = (
        M(1, "region_error", lambda: RegionError),
        S(2, "error"),
        B(3, "succeed"),
        Y(4, "previous_value"),
        B(5, "previous_not_exist"),
    )


class RawGetKeyTtlRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"), S(3, "cf"))


class RawGetKeyTtlResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"),
              U(3, "ttl"), B(4, "not_found"))


# ---------------------------------------------------------------------------
# kvrpcpb.proto — debug (MVCC introspection)
# ---------------------------------------------------------------------------

class MvccValue(Kv):
    FIELDS = (U(1, "start_ts"), Y(2, "value"))


class MvccLock(Kv):
    FIELDS = (U(1, "type"), U(2, "start_ts"), Y(3, "primary"), Y(4, "short_value"))


class MvccWrite(Kv):
    FIELDS = (U(1, "type"), U(2, "start_ts"), U(3, "commit_ts"), Y(4, "short_value"))


class MvccInfo(Kv):
    FIELDS = (
        M(1, "lock", lambda: MvccLock),
        M(2, "writes", lambda: MvccWrite, repeated=True),
        M(3, "values", lambda: MvccValue, repeated=True),
    )


class MvccGetByKeyRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), Y(2, "key"))


class MvccGetByKeyResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"),
              M(3, "info", lambda: MvccInfo))


class MvccGetByStartTsRequest(Kv):
    FIELDS = (M(1, "context", lambda: Context), U(2, "start_ts"))


class MvccGetByStartTsResponse(Kv):
    FIELDS = (M(1, "region_error", lambda: RegionError), S(2, "error"),
              Y(3, "key"), M(4, "info", lambda: MvccInfo))


# ---------------------------------------------------------------------------
# coprocessor.proto
# ---------------------------------------------------------------------------

class KeyRange(Kv):
    FIELDS = (Y(1, "start"), Y(2, "end"))


class CoprRequestPb(Kv):
    """coprocessor.Request — tp 103 = DAG, 104 = Analyze, 105 = Checksum."""

    FIELDS = (
        M(1, "context", lambda: Context),
        I64(2, "tp"),
        Y(3, "data"),
        M(4, "ranges", lambda: KeyRange, repeated=True),
        B(5, "is_cache_enabled"),
        U(6, "cache_if_match_version"),
        U(7, "start_ts"),
    )


class CoprResponsePb(Kv):
    """coprocessor.Response."""

    FIELDS = (
        Y(1, "data"),
        M(2, "region_error", lambda: RegionError),
        M(3, "locked", lambda: LockInfo),
        S(4, "other_error"),
        M(5, "range", lambda: KeyRange),
        M(6, "exec_details", lambda: ExecDetails),
        B(7, "is_cache_hit"),
        U(8, "cache_last_version"),
        B(9, "can_be_cached"),
        M(11, "exec_details_v2", lambda: ExecDetailsV2),
    )


REQ_DAG = 103
REQ_ANALYZE = 104
REQ_CHECKSUM = 105


# -- deadlock.proto (the Deadlock detector service, deadlock.rs:343-391) ----

DEADLOCK_DETECT = 0
DEADLOCK_CLEAN_UP_WAIT_FOR = 1
DEADLOCK_CLEAN_UP = 2


class WaitForEntry(Kv):
    FIELDS = (
        U(1, "txn"),
        U(2, "wait_for_txn"),
        U(3, "key_hash"),
        Y(4, "key"),
        Y(5, "resource_group_tag"),
        U(6, "wait_time"),
    )


class DeadlockRequest(Kv):
    FIELDS = (
        U(1, "tp"),  # DeadlockRequestType enum
        M(2, "entry", lambda: WaitForEntry),
    )


class DeadlockResponse(Kv):
    FIELDS = (
        M(1, "entry", lambda: WaitForEntry),
        U(2, "deadlock_key_hash"),
        M(3, "wait_chain", lambda: WaitForEntry, repeated=True),
    )
