"""Protobuf wire-format primitives and a declarative message base.

Implements the subset of the protobuf encoding spec that kvproto/tipb use:
varint, 64-bit/32-bit fixed, and length-delimited fields, with proto2
("emit when explicitly set") and proto3 ("emit when != default") presence
semantics.  Serialization emits fields in ascending field-number order and
repeated elements in insertion order — the same canonical order protoc's
generated encoders produce, which is what makes byte-identical differential
tests against the real protobuf runtime possible.

No reference counterpart: the reference consumes prost/protobuf-codec
generated code (Cargo.toml:52-99); this is the from-scratch equivalent.
"""

from __future__ import annotations

import struct

# Wire types (encoding spec)
WT_VARINT = 0
WT_FIX64 = 1
WT_LEN = 2
WT_FIX32 = 5

# Field kinds
K_INT = "int"        # int32/int64/uint32/uint64/enum — varint
K_SINT = "sint"      # sint32/sint64 — zigzag varint
K_BOOL = "bool"
K_FIX64 = "fix64"    # fixed64/sfixed64
K_DOUBLE = "double"
K_FIX32 = "fix32"
K_FLOAT = "float"
K_BYTES = "bytes"
K_STR = "str"
K_MSG = "msg"

_VARINT_KINDS = (K_INT, K_SINT, K_BOOL)
_WIRE_TYPE = {
    K_INT: WT_VARINT, K_SINT: WT_VARINT, K_BOOL: WT_VARINT,
    K_FIX64: WT_FIX64, K_DOUBLE: WT_FIX64,
    K_FIX32: WT_FIX32, K_FLOAT: WT_FIX32,
    K_BYTES: WT_LEN, K_STR: WT_LEN, K_MSG: WT_LEN,
}


def write_varint(out: bytearray, v: int) -> None:
    if v < 0:
        v += 1 << 64  # two's-complement 10-byte encoding for negative ints
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _to_i64(v: int) -> int:
    """Interpret a decoded u64 varint as a signed 64-bit value."""
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= 1 << 63 else v


def write_tag(out: bytearray, field_no: int, wire_type: int) -> None:
    write_varint(out, (field_no << 3) | wire_type)


def skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == WT_VARINT:
        _, pos = read_varint(buf, pos)
    elif wire_type == WT_FIX64:
        pos += 8
    elif wire_type == WT_LEN:
        n, pos = read_varint(buf, pos)
        pos += n
    elif wire_type == WT_FIX32:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    if pos > len(buf):
        raise ValueError("truncated field")
    return pos


class Field:
    """One declared field: number, attribute name, kind, and modifiers."""

    __slots__ = ("number", "name", "kind", "repeated", "msg_type", "packed",
                 "signed", "default")

    def __init__(self, number, name, kind, repeated=False, msg_type=None,
                 packed=False, signed=True, default=None):
        self.number = number
        self.name = name
        self.kind = kind
        self.repeated = repeated
        self.msg_type = msg_type  # class or () -> class for forward refs
        self.packed = packed
        self.signed = signed  # varint ints: interpret decoded value as i64
        if default is None and not repeated:
            default = {
                K_INT: 0, K_SINT: 0, K_BOOL: False, K_FIX64: 0, K_FIX32: 0,
                K_DOUBLE: 0.0, K_FLOAT: 0.0, K_BYTES: b"", K_STR: "",
            }.get(kind)
        self.default = default

    def resolve(self):
        mt = self.msg_type
        if mt is not None and not isinstance(mt, type):
            mt = self.msg_type = mt()
        return mt


class PbMessage:
    """Declarative protobuf message.

    Subclasses set ``FIELDS`` (a tuple of ``Field``) and ``SYNTAX`` (2 for
    tipb, 3 for kvproto).  Values are plain attributes; repeated fields are
    lists.  Presence: proto2 emits any field that was explicitly assigned
    (tracked via ``__dict__``), proto3 emits scalars only when != default and
    submessages whenever assigned.
    """

    FIELDS: tuple[Field, ...] = ()
    SYNTAX = 3
    __by_number = None  # per-class decode index, built lazily

    def __init__(self, **kwargs):
        for f in self.FIELDS:
            if f.repeated:
                setattr(self, f.name, [])
        for k, v in kwargs.items():
            if v is not None:
                setattr(self, k, v)

    # -- encode ------------------------------------------------------------

    @classmethod
    def _sorted_fields(cls):
        fs = cls.__dict__.get("_PbMessage__sorted")
        if fs is None:
            fs = tuple(sorted(cls.FIELDS, key=lambda f: f.number))
            setattr(cls, "_PbMessage__sorted", fs)
        return fs

    def encode(self) -> bytes:
        out = bytearray()
        for f in self._sorted_fields():
            self._encode_field(out, f)
        return bytes(out)

    def _present(self, f: Field, v) -> bool:
        if self.SYNTAX == 2:
            return f.name in self.__dict__
        if f.kind == K_MSG:
            return v is not None
        return v != f.default

    def _encode_field(self, out: bytearray, f: Field) -> None:
        v = self.__dict__.get(f.name)
        if f.repeated:
            if not v:
                return
            if f.packed and f.kind in _VARINT_KINDS:
                payload = bytearray()
                for item in v:
                    write_varint(payload, zigzag(item) if f.kind == K_SINT else int(item))
                write_tag(out, f.number, WT_LEN)
                write_varint(out, len(payload))
                out += payload
            elif f.packed and f.kind in (K_FIX64, K_DOUBLE, K_FIX32, K_FLOAT):
                payload = bytearray()
                for item in v:
                    self._encode_scalar(payload, f, item)
                write_tag(out, f.number, WT_LEN)
                write_varint(out, len(payload))
                out += payload
            else:
                for item in v:
                    write_tag(out, f.number, _WIRE_TYPE[f.kind])
                    self._encode_scalar(out, f, item)
            return
        if v is None or not self._present(f, v):
            return
        write_tag(out, f.number, _WIRE_TYPE[f.kind])
        self._encode_scalar(out, f, v)

    @staticmethod
    def _encode_scalar(out: bytearray, f: Field, v) -> None:
        if f.kind == K_INT:
            write_varint(out, int(v))
        elif f.kind == K_SINT:
            write_varint(out, zigzag(int(v)))
        elif f.kind == K_BOOL:
            write_varint(out, 1 if v else 0)
        elif f.kind == K_FIX64:
            out += struct.pack("<Q", int(v) & ((1 << 64) - 1))
        elif f.kind == K_DOUBLE:
            out += struct.pack("<d", float(v))
        elif f.kind == K_FIX32:
            out += struct.pack("<I", int(v) & 0xFFFFFFFF)
        elif f.kind == K_FLOAT:
            out += struct.pack("<f", float(v))
        elif f.kind == K_BYTES:
            b = bytes(v)
            write_varint(out, len(b))
            out += b
        elif f.kind == K_STR:
            b = v.encode("utf-8")
            write_varint(out, len(b))
            out += b
        elif f.kind == K_MSG:
            b = v.encode()
            write_varint(out, len(b))
            out += b
        else:
            raise ValueError(f"unknown kind {f.kind}")

    # -- decode ------------------------------------------------------------

    @classmethod
    def decode(cls, buf: bytes):
        msg = cls()
        cls._decode_into(msg, buf)
        return msg

    @classmethod
    def _index(cls):
        idx = cls.__dict__.get("_PbMessage__by_number")
        if idx is None:
            idx = {f.number: f for f in cls.FIELDS}
            setattr(cls, "_PbMessage__by_number", idx)
        return idx

    @classmethod
    def _decode_into(cls, msg, buf: bytes) -> None:
        idx = cls._index()
        pos = 0
        n = len(buf)
        while pos < n:
            key, pos = read_varint(buf, pos)
            field_no, wt = key >> 3, key & 7
            f = idx.get(field_no)
            if f is None:
                pos = skip_field(buf, pos, wt)
                continue
            if f.repeated and wt == WT_LEN and f.kind in (
                    K_INT, K_SINT, K_BOOL, K_FIX64, K_DOUBLE, K_FIX32, K_FLOAT):
                # packed run (decoders must accept packed for any repeated
                # scalar regardless of declared packedness)
                ln, pos = read_varint(buf, pos)
                end = pos + ln
                vals = getattr(msg, f.name)
                while pos < end:
                    v, pos = cls._decode_scalar_at(buf, pos, f)
                    vals.append(v)
                continue
            if f.kind == K_MSG:
                if wt != WT_LEN:
                    raise ValueError(f"field {field_no}: expected LEN wire type")
                ln, pos = read_varint(buf, pos)
                sub = f.resolve().decode(buf[pos:pos + ln])
                pos += ln
                if f.repeated:
                    getattr(msg, f.name).append(sub)
                else:
                    setattr(msg, f.name, sub)
                continue
            if wt != _WIRE_TYPE[f.kind]:
                # Wire type disagrees with the declared kind (e.g. a varint
                # field sent as FIX64). Decoding per the declared kind would
                # read the wrong width and silently misparse everything after;
                # protoc-generated decoders skip such fields — do the same.
                pos = skip_field(buf, pos, wt)
                continue
            v, pos = cls._decode_scalar_at(buf, pos, f, wt)
            if f.repeated:
                getattr(msg, f.name).append(v)
            else:
                setattr(msg, f.name, v)

    @staticmethod
    def _decode_scalar_at(buf, pos, f: Field, wt=None):
        kind = f.kind
        if kind in (K_INT, K_SINT, K_BOOL):
            raw, pos = read_varint(buf, pos)
            if kind == K_SINT:
                return unzigzag(raw), pos
            if kind == K_BOOL:
                return bool(raw), pos
            return (_to_i64(raw) if f.signed else raw), pos
        if kind in (K_FIX64, K_DOUBLE):
            if pos + 8 > len(buf):
                raise ValueError("truncated fixed64")
            v = struct.unpack_from("<d" if kind == K_DOUBLE else "<Q", buf, pos)[0]
            return v, pos + 8
        if kind in (K_FIX32, K_FLOAT):
            if pos + 4 > len(buf):
                raise ValueError("truncated fixed32")
            v = struct.unpack_from("<f" if kind == K_FLOAT else "<I", buf, pos)[0]
            return v, pos + 4
        if kind in (K_BYTES, K_STR):
            ln, pos = read_varint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated bytes")
            raw = buf[pos:pos + ln]
            return (raw.decode("utf-8") if kind == K_STR else bytes(raw)), pos + ln
        raise ValueError(f"unknown kind {kind}")

    # -- misc --------------------------------------------------------------

    def __getattr__(self, name):
        # protobuf getter semantics: unset scalar fields read as their
        # default, unset submessages as None (only called when not in
        # __dict__, so set fields keep normal attribute access)
        for f in type(self).FIELDS:
            if f.name == name:
                if f.repeated:
                    v = []
                    self.__dict__[name] = v
                    return v
                return None if f.kind == K_MSG else f.default
        raise AttributeError(f"{type(self).__name__} has no field {name!r}")

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        return self.encode() == other.encode()

    def __repr__(self):
        parts = []
        for f in self.FIELDS:
            v = self.__dict__.get(f.name)
            if v not in (None, [], b"", ""):
                parts.append(f"{f.name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"
