"""Protobuf-compatible wire layer (kvproto / tipb contract).

The reference's entire external contract is protobuf over gRPC: TiDB sends
``tipb.DAGRequest`` inside ``coprocessor.Request.data`` and expects
``tipb.SelectResponse`` bytes back inside ``coprocessor.Response.data``
(src/server/service/kv.rs:129-303, Cargo.toml:165,220).  This package
implements that contract with a hand-rolled, dependency-free protobuf codec:

* ``wire``       — varint / tag / length-delimited primitives and a
                   declarative ``PbMessage`` base (proto2 + proto3 semantics)
* ``tipb_pb``    — the tipb messages the coprocessor speaks
* ``kvproto_pb`` — coprocessor.Request/Response, kvrpcpb txn/raw messages,
                   errorpb subset

Field numbers are reconstructed from the public pingcap/kvproto and
pingcap/tipb protos the reference pins; differential tests compile the
reconstructed ``.proto`` files with the baked-in protoc and assert
byte-identical encodings against the real protobuf runtime.
"""
