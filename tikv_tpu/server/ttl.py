"""Raw-KV TTL reclamation.

Re-expression of ``src/server/ttl`` (``ttl_checker.rs:32`` periodic checker +
``ttl_compaction_filter.rs:14``): reads already filter expired raw values
(storage.py `_decode_raw_value`), but the bytes stay resident until something
physically deletes them.  The reference drops them inside RocksDB compaction
— a per-store local delete.  Here the sweep goes through the REPLICATED
delete path instead (raw_batch_delete → raft), so replicas stay byte-
identical and the consistency-check observer never flags TTL reclamation as
divergence.  Expiry is a deterministic function of the stored expire stamp,
so leader-driven deletion loses nothing a replica-local filter would keep.
"""

from __future__ import annotations

import time

from ..storage.engine import CF_DEFAULT
from ..storage.storage import _NO_TTL, RAW_PREFIX
from ..util import codec


class TtlChecker:
    """Periodic expired-raw-entry sweeper over one store's storage."""

    def __init__(self, storage, batch: int = 512):
        self.storage = storage
        self.batch = batch
        self.swept = 0

    def sweep(self, ctx: dict | None = None, now: float | None = None) -> int:
        """One pass: scan the raw keyspace for expired candidates, then
        delete them in bounded batches via ``raw_delete_if_expired`` —
        which RE-CHECKS each key under the raw latches, so a raw_put racing
        the sweep (fresh live value landing after this scan's snapshot)
        is never destroyed.  Returns entries reclaimed."""
        now = now if now is not None else time.time()
        snap = self.storage.engine.snapshot(ctx)
        end = RAW_PREFIX[:-1] + bytes([RAW_PREFIX[-1] + 1])
        expired: list[bytes] = []
        removed = 0
        for k, stored in snap.scan_cf(CF_DEFAULT, RAW_PREFIX, end):
            if len(stored) < 8:
                continue
            expire = codec.decode_u64(stored, len(stored) - 8)
            if expire != _NO_TTL and expire <= int(now):
                expired.append(k[len(RAW_PREFIX):])
                if len(expired) >= self.batch:
                    removed += self.storage.raw_delete_if_expired(expired, ctx, now)
                    expired = []
        if expired:
            removed += self.storage.raw_delete_if_expired(expired, ctx, now)
        self.swept += removed
        return removed
