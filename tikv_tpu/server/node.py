"""Node lifecycle: bootstrap a store against PD and keep it beating.

Re-expression of ``src/server/node.rs`` (:61 Node, :153 bootstrap: alloc store
id from PD, bootstrap the first region) and the raftstore PD worker
(``store/worker/pd.rs:101``): periodic store heartbeats (capacity/usage) and
per-region heartbeats from leaders, plus PD-driven region split when a region
grows past the configured size.
"""

from __future__ import annotations

import threading
import time

from ..pd.client import PdClient
from ..raft.region import Peer as RegionPeer, Region, RegionEpoch
from ..raft.store import Store, Transport
from ..util import keys
from ..util.metrics import REGISTRY

REGION_COUNT = REGISTRY.gauge(
    "tikv_raftstore_region_count", "Regions hosted by this store")
LEADER_COUNT = REGISTRY.gauge(
    "tikv_raftstore_leader_count", "Regions this store leads")
STORE_USED_BYTES = REGISTRY.gauge(
    "tikv_store_size_bytes", "Engine resident bytes, by type")

FIRST_REGION_ID = 1


class Node:
    def __init__(
        self,
        pd: PdClient,
        transport: Transport,
        store_id: int | None = None,
        split_threshold_keys: int | None = None,
        engine=None,
        split_qps_threshold: float | None = None,
        consistency_check_interval: float | None = None,
        raft_log=None,
    ):
        self.pd = pd
        self.store_id = store_id or pd.alloc_id()
        self.store = Store(self.store_id, transport, engine=engine, raft_log=raft_log)
        # server nodes run the apply pipeline (apply.rs ApplyBatchSystem):
        # committed data entries apply off the raft thread
        self.store.enable_apply_pipeline()
        self.split_threshold_keys = split_threshold_keys
        # load-based auto split (store/worker/split_controller.rs): write
        # ops per region per heartbeat; sustained load above the threshold
        # for two consecutive beats splits the region at its middle key
        self.split_qps_threshold = split_qps_threshold
        self._write_ops: dict[int, int] = {}
        self._hot_beats: dict[int, int] = {}
        self.consistency_check_interval = consistency_check_interval
        self._last_consistency = 0.0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # faults escaping the raft loop (e.g. injected failpoints) land here
        # instead of silently killing the daemon thread; apply re-delivery is
        # handled by the store (Peer.handle_ready rewinds on failure)
        self.thread_errors: list[Exception] = []
        # callables invoked once per store heartbeat (memory-trace polling,
        # CDC idle reaping, ...); exceptions land in thread_errors
        self.heartbeat_hooks: list = []
        pd.put_store(self.store_id)
        self.store.split_observers.append(self._on_split)
        # always counted (one dict increment per applied command): the
        # region-heartbeat load that feeds PD's hot-region leader balance
        # needs real numbers whether or not load SPLITTING is enabled
        self.store.apply_observers.append(self._count_writes)

    def _count_writes(self, store, region, cmd) -> None:
        ops = cmd.get("ops")
        if ops:
            self._write_ops[region.id] = self._write_ops.get(region.id, 0) + len(ops)

    # -- bootstrap ----------------------------------------------------------

    def try_bootstrap_cluster(self, all_store_ids: list[int]) -> Region | None:
        """First node up bootstraps region 1 across the given stores."""
        if self.pd.get_region_by_id(FIRST_REGION_ID) is not None:
            return None
        peers = [RegionPeer(self.pd.alloc_id(), sid) for sid in all_store_ids]
        region = Region(FIRST_REGION_ID, b"", b"", RegionEpoch(), peers)
        self.pd.bootstrap_region(region)
        return region

    def create_region_peers(self) -> None:
        """Create local peers for every PD region placed on this store."""
        region = self.pd.get_region_by_id(FIRST_REGION_ID)
        if region is not None and region.peer_on_store(self.store_id) is not None:
            if region.id not in self.store.peers:
                self.store.create_peer(region)

    # -- background loops ---------------------------------------------------

    def start(self, tick_interval: float = 0.05, heartbeat_interval: float = 0.5,
              pollers: int = 2, use_batch_system: bool = True) -> None:
        if use_batch_system:
            # batch-system mode (batch.rs Poller pool): per-region mailboxes,
            # N pollers, a tick broadcaster — no O(all-regions) loop body
            from ..raft.fsm_system import BatchSystem, Router as FsmRouter
            from ..raft.store import StoreFsmDelegate

            router = FsmRouter()
            self.store.attach_fsm_router(router)
            self._batch_system = BatchSystem(
                router, lambda: StoreFsmDelegate(self.store),
                pollers=pollers, name=f"raftstore-{self.store_id}",
            )
            self._batch_system.errors = self.thread_errors  # share the sink
            self._batch_system.spawn()

            def raft_loop():  # tick broadcaster only
                while not self._stop.is_set():
                    router.broadcast(lambda a: ("tick",))
                    if self.store._compact_requested.is_set():
                        self.store._compact_requested.clear()
                        router.broadcast(lambda a: ("compact",))
                    self._stop.wait(tick_interval)
        else:
            def raft_loop():
                last_tick = 0.0
                while not self._stop.is_set():
                    try:
                        moved = self.store.process_messages()
                        moved |= self.store.handle_readies()
                        now = time.monotonic()
                        if now - last_tick >= tick_interval:
                            self.store.tick()
                            last_tick = now
                    except Exception as exc:  # keep the store beating on faults
                        if len(self.thread_errors) < 128:
                            self.thread_errors.append(exc)
                        moved = False
                    if not moved:
                        time.sleep(0.001)

        def pd_loop():
            while not self._stop.is_set():
                try:
                    stats = {"regions": len(self.store.peers)}
                    mem_bytes = getattr(self.store.engine, "mem_bytes", None)
                    if mem_bytes is not None:
                        # size-weighted balance input (store_heartbeat
                        # capacity/used stats, pd.rs:101)
                        stats["used_bytes"] = mem_bytes()
                    REGION_COUNT.set(len(self.store.peers))
                    if "used_bytes" in stats:
                        STORE_USED_BYTES.set(stats["used_bytes"], type="memtable")
                    wal_bytes = getattr(self.store.engine, "wal_bytes", None)
                    if wal_bytes is not None:
                        STORE_USED_BYTES.set(wal_bytes(), type="wal")
                    repl = self.pd.store_heartbeat(self.store_id, stats)
                    if isinstance(repl, dict):
                        # DrAutoSync state rides the heartbeat response
                        # (replication_mode.rs); majority mode clears it
                        self.store.set_replication_mode(repl)
                    led = set()
                    for peer in list(self.store.peers.values()):
                        if peer.node.is_leader():
                            led.add(peer.region.id)
                            op = self.pd.region_heartbeat(
                                peer.region.clone(), self.store_id,
                                load=self._write_ops.get(peer.region.id, 0))
                            if op:
                                self._execute_operator(peer, op)
                            self._maybe_split(peer)
                            self._maybe_load_split(peer, heartbeat_interval)
                    # counts accrued while FOLLOWING must not look like load
                    # the moment this store wins leadership
                    LEADER_COUNT.set(len(led))
                    for rid in list(self._write_ops):
                        if rid not in led:
                            self._write_ops.pop(rid, None)
                            self._hot_beats.pop(rid, None)
                    self._maybe_consistency_check()
                    self.store.request_log_compaction()
                    for hook in self.heartbeat_hooks:
                        hook()
                except Exception as exc:  # PD briefly unreachable: keep beating
                    if len(self.thread_errors) < 128:
                        self.thread_errors.append(exc)
                self._stop.wait(heartbeat_interval)

        for fn in (raft_loop, pd_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        bs = getattr(self, "_batch_system", None)
        if bs is not None:
            bs.shutdown()
        for t in self._threads:
            t.join(timeout=2)
        self.store.stop_apply_pipeline()

    def pump(self) -> None:
        """Synchronous message pump for RaftKv when loops aren't running.
        Not valid in batch-system mode (pollers own per-region state)."""
        if self.store.fsm_router is not None:
            return  # pollers are driving; a sync sweep here would race them
        self.store.process_messages()
        self.store.handle_readies()

    # -- split checking (split_check worker + AutoSplitController) ----------

    def _maybe_split(self, peer) -> None:
        if self.split_threshold_keys is None:
            return
        ks = self._scan_region_keys(peer, self.split_threshold_keys + 1)
        if len(ks) <= self.split_threshold_keys:
            return
        self._propose_middle_split(peer, ks)

    def _scan_region_keys(self, peer, limit: int) -> list:
        eng = self.store.engine
        start = keys.data_key(peer.region.start_key)
        end = keys.data_end_key(peer.region.end_key)
        return [k for k, _ in eng.scan_cf("write", start, end, limit=limit)]

    def _propose_middle_split(self, peer, ks: list) -> None:
        """THE split-point rule, shared by size- and load-based splitting:
        strip the MVCC ts suffix ONLY — region boundaries live in the opaque
        engine key space (the memcomparable-encoded form for txn data),
        never decoded: a raw-decoded boundary would not be order-consistent
        with the stored keys (same rule as the reference, where split-check
        emits origin_key(engine key) verbatim)."""
        if len(ks) < 2:
            return
        split_at = keys.origin_key(ks[len(ks) // 2])
        from ..storage.txn_types import split_ts

        try:
            split_at, _ = split_ts(split_at)
        except ValueError:
            pass  # no ts suffix (raw-mode data)
        if not peer.region.contains(split_at) or split_at == peer.region.start_key:
            return
        new_region_id = self.pd.alloc_id()
        new_pids = [self.pd.alloc_id() for _ in peer.region.peers]
        peer.propose_split(split_at, new_region_id, new_pids, lambda r: None)

    def _on_split(self, store, old: Region, new: Region) -> None:
        self.pd.report_split(old.clone(), new.clone())

    # -- PD operator execution (heartbeat-response scheduling) ---------------

    def _execute_operator(self, peer, op: dict) -> None:
        """Run ONE scheduling order from the PD heartbeat response (the
        raftstore pd worker executing pdpb::RegionHeartbeatResponse)."""
        kind = op.get("type")
        if kind == "transfer_leader":
            if not peer.transfer_leader_to(op["peer_id"]):
                # target not caught up yet (the MsgTimeoutNow gate): put the
                # operator back so a later heartbeat retries it
                add_op = getattr(self.pd, "add_operator", None)
                if add_op is not None:
                    add_op(peer.region.id, op)
        elif kind == "add_peer":
            peer.propose_cmd(
                {
                    "epoch": (peer.region.epoch.conf_ver, peer.region.epoch.version),
                    "ops": [],
                    "admin": ("conf_change", "add", self.pd.alloc_id(), op["store_id"]),
                },
                lambda r: None,
            )
        elif kind == "remove_peer":
            peer.propose_cmd(
                {
                    "epoch": (peer.region.epoch.conf_ver, peer.region.epoch.version),
                    "ops": [],
                    "admin": ("conf_change", "remove", op["peer_id"], 0),
                },
                lambda r: None,
            )

    def _maybe_load_split(self, peer, interval: float) -> None:
        """AutoSplitController: a region whose sustained write rate exceeds
        the threshold for two consecutive heartbeats splits at its middle
        key (split_controller.rs, simplified to write QPS)."""
        if self.split_qps_threshold is None:
            return
        rid = peer.region.id
        ops = self._write_ops.pop(rid, 0)
        if ops / max(interval, 1e-6) >= self.split_qps_threshold:
            self._hot_beats[rid] = self._hot_beats.get(rid, 0) + 1
        else:
            self._hot_beats.pop(rid, None)
            return
        if self._hot_beats[rid] < 2:
            return
        self._hot_beats.pop(rid, None)
        self._propose_middle_split(peer, self._scan_region_keys(peer, 2048))

    def _maybe_consistency_check(self) -> None:
        """Periodic compute_hash proposals on led regions
        (CONSISTENCY_CHECK tick)."""
        if self.consistency_check_interval is None:
            return
        now = time.monotonic()
        if now - self._last_consistency < self.consistency_check_interval:
            return
        self._last_consistency = now
        for peer in list(self.store.peers.values()):
            if peer.node.is_leader():
                peer.schedule_consistency_check()
