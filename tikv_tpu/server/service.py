"""The KV service: the store's full external API surface.

Re-expression of the gRPC ``Tikv`` service (``src/server/service/kv.rs``; the
handler inventory is SURVEY.md Appendix A): transactional KV, raw KV, and
coprocessor, plus cluster-internal helpers.  Handlers take/return plain
wire-codable dicts so the same functions serve in-process calls and the TCP
server's ``batch_commands`` multiplexing.

Errors are returned as ``{"error": {...}}`` region errors / key errors the
way the reference maps storage errors into kvproto errors.
"""

from __future__ import annotations

import threading

from ..copr.endpoint import CoprRequest, Endpoint, REQ_TYPE_CHECKSUM, REQ_TYPE_DAG
from ..raft.region import EpochError, NotLeaderError
from ..storage.mvcc.reader import KeyIsLockedError, WriteConflictError
from ..storage.mvcc.txn import AlreadyExistsError, TxnError
from ..storage.storage import Storage
from ..storage.txn import commands as cmds
from ..storage.txn_types import Key, Mutation, MutationType


def _mutation_from_wire(m: dict) -> Mutation:
    op = MutationType(m["op"])
    return Mutation(op, Key.from_raw(m["key"]), m.get("value"))


def _rewrite_from_wire(req: dict) -> tuple[bytes, bytes] | None:
    if req.get("rewrite_old") is None:
        return None
    return (req["rewrite_old"], req["rewrite_new"])


def _err(e: Exception) -> dict:
    if isinstance(e, KeyIsLockedError):
        return {
            "locked": {
                "key": e.key,
                "primary": e.lock.primary,
                "lock_ts": e.lock.ts,
                "ttl": e.lock.ttl,
            }
        }
    if isinstance(e, WriteConflictError):
        return {
            "conflict": {
                "key": e.key,
                "start_ts": e.start_ts,
                "conflict_start_ts": e.conflict_start_ts,
                "conflict_commit_ts": e.conflict_commit_ts,
            }
        }
    if isinstance(e, AlreadyExistsError):
        return {"already_exists": {"key": e.key}}
    if isinstance(e, NotLeaderError):
        return {"not_leader": {"region_id": e.region_id, "leader_store": e.leader_store}}
    if isinstance(e, EpochError):
        return {"epoch_not_match": {}}
    if type(e).__name__ == "DataNotReadyError":
        # stale read above the replica's watermark (raftkv stale path): a
        # TYPED refusal — the carried ``resolved`` ts drives the client's
        # watermark-aware backoff (util.retry data_not_ready class) and the
        # read plane's refusal hints ride the same dict
        return {"data_not_ready": {
            "region_id": getattr(e, "region_id", None),
            "read_ts": getattr(e, "read_ts", None),
            "resolved": getattr(e, "resolved", None),
        }}
    retry_after = getattr(e, "retry_after_s", None)
    if retry_after is not None or type(e).__name__ in ("SchedTooBusy", "ServerBusyError"):
        # ServerIsBusy shape: the retry-after hint survives the wire so the
        # client-side retry policy can honor it (util.retry)
        busy = {}
        if retry_after is not None:
            busy["retry_after_ms"] = int(retry_after * 1000)
        return {"server_is_busy": busy}
    if type(e).__name__ == "DeadlineExceeded":
        return {"deadline_exceeded": {}}
    return {"other": str(e)}


class KvService:
    """All handlers of one store (kv.rs handler inventory)."""

    def __init__(
        self, storage: Storage, copr: Endpoint | None = None, copr_v2=None,
        resource_tags=None, debugger=None, cdc=None, pd=None, importer=None,
        raft_router=None, gc_worker=None, lock_manager=None, resolved_ts=None,
        diagnostics=None, keys_rotator=None, read_plane=None, overload=None,
    ):
        self.storage = storage
        self.copr = copr
        # overload control plane (docs/robustness.md "Overload"): per-tenant
        # quota admission on the read entries — over-quota work defers a
        # bounded wait then sheds as ServerIsBusy with a refill-deficit
        # retry_after hint.  None (the default) gates nothing.
        self.overload = overload if overload is not None \
            else getattr(copr, "overload", None)
        # the read-degradation ladder (server/read_plane.py): wraps the read
        # handlers so NotLeader/DataNotReady region errors forward one hop,
        # degrade to follower stale serving, or refuse with hints.  None
        # (embedded assemblies) keeps the old bounce-the-error behavior.
        self.read_plane = read_plane
        self.copr_v2 = copr_v2
        self.resource_tags = resource_tags
        self.debugger = debugger
        self.cdc = cdc
        self.pd = pd
        self.importer = importer
        self.gc_worker = gc_worker
        self.lock_manager = lock_manager
        self.resolved_ts = resolved_ts
        self.diagnostics = diagnostics
        self.keys_rotator = keys_rotator
        # peer raft ingress: the local Store messages are routed into
        # (service/kv.rs raft:612 / batch_raft:649 / snapshot:692).
        # The assembler is built eagerly: lazy init would race between
        # connection threads and orphan a concurrent transfer's first chunk.
        self.raft_router = raft_router
        from ..raft.net import SnapshotAssembler

        self._snap_assembler = SnapshotAssembler()
        # Per-instance: the 2-slot long-poll bound must not be shared across
        # stores in one process (a poller on one store would degrade
        # cdc_events long-polls on unrelated stores to immediate returns).
        self._cdc_longpoll_slots = threading.Semaphore(2)
        # wire-DAG parse memo: clients resend the same plan on every request
        # of a workload, and dag_from_wire + executor descriptor construction
        # was a fixed per-request tax on the wire path.  Keyed by the plan's
        # canonical wire bytes; DagRequests are treated as immutable by every
        # serving path (the streaming handler copies before re-framing).
        self._dag_memo: dict[bytes, object] = {}
        self._dag_memo_mu = threading.Lock()
        # device-eligibility verdicts for owner routing, keyed by the memoized
        # DagRequest object (id + identity check guards against reuse)
        self._dag_eligible_memo: dict[int, tuple] = {}

    _HANDLER_PREFIXES = (
        "kv_", "raw_", "coprocessor", "mvcc_", "debug_", "cdc_", "import_", "raft_",
        "backup", "diagnostics_",
    )
    # RPCs whose reference names carry no family prefix (kv.rs:358-1061)
    _EXTRA_HANDLERS = frozenset(
        {
            "register_lock_observer", "check_lock_observer", "remove_lock_observer",
            "physical_scan_lock", "unsafe_destroy_range", "get_store_safe_ts",
            "get_lock_wait_info", "deadlock_detect",
        }
    )

    # -- peer raft ingress (kv.rs raft/batch_raft/snapshot handlers) --------

    def _router(self):
        if self.raft_router is None:
            raise RuntimeError("peer raft service not enabled on this node")
        return self.raft_router

    def raft_message(self, req: dict) -> dict:
        """Single RaftMessage ingress (kv.rs:612)."""
        from ..raft import net as raft_net

        self._router().enqueue_message(raft_net.rmsg_from_wire(req["msg"]))
        return {}

    def raft_batch(self, req: dict) -> dict:
        """BatchRaftMessage ingress (kv.rs:649): the peer stream's one frame
        shape — every buffered message of a flush interval together."""
        from ..raft import net as raft_net

        router = self._router()
        for t in req["msgs"]:
            router.enqueue_message(raft_net.rmsg_from_wire(t))
        return {}

    def raft_snapshot_chunk(self, req: dict) -> dict:
        """Chunked snapshot stream ingress (kv.rs snapshot:692, snap.rs:260):
        chunks joined per transfer id; the completed snapshot message enters
        the store like any other raft message."""
        from ..raft import net as raft_net

        router = self._router()
        rmsg = self._snap_assembler.add_chunk(req)
        if rmsg is not None:
            router.enqueue_message(rmsg)
        return {}

    def raft_check_leader(self, req: dict) -> dict:
        """resolved-ts CheckLeader (advance.rs:211 service side): acknowledge
        matching (term, leader) claims and adopt disseminated watermarks."""
        if self.resolved_ts is None:
            return {"accepted": []}
        return self.resolved_ts.handle_check_leader(req)

    def debug_rotate_data_key(self, req: dict) -> dict:
        """Encryption-at-rest data-key rotation on a RUNNING store
        (manager/mod.rs rotation surface): new engine/raft-log files encrypt
        under the fresh key; nothing on disk is rewritten."""
        if self.keys_rotator is None:
            return {"error": {"other": "encryption at rest not enabled"}}
        try:
            return self.keys_rotator()
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def debug_consistency(self, req: dict) -> dict:
        """Consistency-check results (tikv-ctl consistency-check view):
        recorded region hashes and any detected divergences."""
        router = self._router()
        return {
            "hashes": {
                rid: {"index": idx, "hash": h}
                for rid, (idx, h) in list(router.consistency_hashes.items())
            },
            "inconsistent": dict(router.inconsistent_regions),
        }

    def debug_consistency_check(self, req: dict) -> dict:
        """Trigger a consistency-check round NOW (``ctl.py
        consistency-check --trigger``): propose compute_hash on every led
        region (or just ``region_id``).  The round completes asynchronously
        through raft apply; poll ``debug_consistency`` for results."""
        router = self._router()
        rid = req.get("region_id")
        scheduled = []
        for region_id, peer in list(router.peers.items()):
            if rid is not None and region_id != rid:
                continue
            if peer.node.is_leader():
                peer.schedule_consistency_check()
                scheduled.append(region_id)
        return {"scheduled": sorted(scheduled)}

    def debug_integrity(self, req: dict) -> dict:
        """Integrity-plane state (docs/integrity.md; ``ctl.py integrity``
        and the status server's ``/debug/integrity``): per-region image
        fingerprints + apply points, the quarantine ledger, scrubber
        cadence/progress, and shadow-read sample/mismatch counts."""
        if self.copr is None:
            return {"error": {"other": "coprocessor endpoint not wired"}}
        return self.copr.integrity_snapshot()

    # -- ImportSST service (sst_service.rs: download + ingest) --------------

    def _importer(self):
        if self.importer is None:
            raise RuntimeError("import service not enabled")
        return self.importer

    def import_download(self, req: dict) -> dict:
        try:
            return self._importer().download(req["name"], _rewrite_from_wire(req))
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def import_ingest(self, req: dict) -> dict:
        """Ingest a (downloaded) backup file as committed writes at
        restore_ts — through the raft propose path when the engine is a
        RaftKv, exactly like the reference's IngestSst command."""
        try:
            return self._importer().restore(
                self.storage.engine, req["name"], req["restore_ts"],
                req.get("context"), _rewrite_from_wire(req),
            )
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    # -- ChangeData service (cdcpb over the multiplexed transport) ----------

    def _cdc(self):
        if self.cdc is None:
            raise RuntimeError("cdc service not enabled")
        return self.cdc

    def cdc_register(self, req: dict) -> dict:
        return self._cdc().register(req["region_id"], req.get("checkpoint_ts", 0))

    def cdc_events(self, req: dict) -> dict:
        # timeout_ms: long-poll — block until events arrive or the deadline.
        # The wait parks a shared worker thread, so concurrent long-pollers
        # are bounded; excess pollers degrade to an immediate (empty) return
        # instead of starving every other RPC on the store
        timeout = min(int(req.get("timeout_ms", 0)), 10_000) / 1000.0
        if timeout > 0:
            if not self._cdc_longpoll_slots.acquire(blocking=False):
                timeout = 0.0
        try:
            return self._cdc().events(
                req["sub_id"], req.get("after_seq", 0), req.get("limit", 1024), timeout
            )
        finally:
            if timeout > 0:
                self._cdc_longpoll_slots.release()

    def cdc_deregister(self, req: dict) -> dict:
        return self._cdc().deregister(req["sub_id"])

    # -- Debug service (debug.rs over gRPC; read-only surface -- the
    # destructive commands like unsafe-recover are offline-only by design) --

    def _debug(self):
        if self.debugger is None:
            raise RuntimeError("debug service not enabled")
        return self.debugger

    def debug_region_info(self, req: dict) -> dict:
        info = self._debug().region_info(req["region_id"])
        return {"info": info} if info is not None else {"error": {"other": "region not found"}}

    def debug_region_properties(self, req: dict) -> dict:
        props = self._debug().region_properties(req["region_id"])
        return {"props": props} if props is not None else {"error": {"other": "region not found"}}

    def debug_bad_regions(self, req: dict) -> dict:
        return {"bad": self._debug().bad_regions()}

    def debug_all_regions(self, req: dict) -> dict:
        return {"regions": self._debug().all_regions()}

    def dispatch(self, method: str, req: dict):
        """Invoke a handler with resource-group attribution (the tagged-future
        wrapper from resource_metering/cpu/future_ext.rs).  Only methods with
        handler prefixes are reachable from the wire — attributes like
        ``storage`` can never be called remotely."""
        if not method.startswith(self._HANDLER_PREFIXES) and method not in self._EXTRA_HANDLERS:
            return {"error": {"other": f"unknown method {method}"}}
        handler = getattr(self, method, None)
        if handler is None:
            return {"error": {"other": f"unknown method {method}"}}
        tag = (req.get("context") or {}).get("resource_group", b"default")
        if self.resource_tags is not None:
            with self.resource_tags.attach(tag):
                return handler(req)
        return handler(req)

    def raw_coprocessor(self, req: dict) -> dict:
        """Coprocessor V2 plugin dispatch (kv.rs:330 raw_coprocessor)."""
        if self.copr_v2 is None:
            return {"error": {"other": "coprocessor v2 not enabled"}}
        return self.copr_v2.handle_request(req)

    # -- transactional KV ---------------------------------------------------

    def _serve_read(self, method: str, req: dict, local) -> dict:
        """Read-degradation ladder entry (docs/stale_reads.md): serve
        locally; a NotLeader/DataNotReady region error hands the response
        to the read plane, which forwards ONE hop to the leader (loop-
        guarded by the ``forwarded`` ctx flag), degrades to a follower
        stale read when the request permits, or returns the typed refusal
        carrying the leader hint + this store's ``safe_ts``.  With no read
        plane wired the behavior is exactly the pre-ladder one."""
        resp = local(req)
        if self.read_plane is None or not isinstance(resp, dict):
            return resp
        err = resp.get("error")
        if not isinstance(err, dict) or not ({"not_leader", "data_not_ready"} & err.keys()):
            return resp
        return self.read_plane.degrade(self, method, req, resp, local)

    def _admit_overload(self, req: dict, where: str) -> dict | None:
        """Per-tenant quota gate on a read entry: None = admitted (possibly
        after a bounded defer), else the typed ServerIsBusy error dict with
        ``retry_after_ms`` riding the wire (docs/robustness.md).

        This is the WIRE BOUNDARY: a client-supplied admission marker is
        stripped before admitting — `_overload_admitted` is an in-process
        nesting contract (service -> scheduler), never a client claim — and
        a missing context is materialized onto the request so the stamp
        reaches the nested layers (otherwise the scheduler would charge a
        second token against a fresh dict)."""
        ov = self.overload
        if ov is None:
            return None
        ctx = req.get("context")
        if not isinstance(ctx, dict):
            ctx = req["context"] = {}
        ctx.pop("_overload_admitted", None)
        try:
            ov.admit(ctx, where=where)
        except Exception as e:  # noqa: BLE001 — ServerBusyError, typed
            return {"error": _err(e)}
        return None

    def _note_read_bytes(self, req: dict, nbytes: int) -> None:
        """Post-serve read-byte charge against the tenant's byte bucket
        (response size is unknown at admission; the debt gates the
        tenant's NEXT admission)."""
        if self.overload is not None and nbytes:
            self.overload.note_bytes(req.get("context"), nbytes)

    def kv_get(self, req: dict) -> dict:
        busy = self._admit_overload(req, "kv")
        if busy is not None:
            return busy
        resp = self._serve_read("kv_get", req, self._kv_get_local)
        if isinstance(resp, dict) and resp.get("value"):
            self._note_read_bytes(req, len(resp["value"]))
        return resp

    def _kv_get_local(self, req: dict) -> dict:
        try:
            v = self.storage.get(
                req["key"], req["version"], req.get("context"),
                bypass_locks=frozenset(req.get("bypass_locks", ())),
            )
            return {"value": v, "not_found": v is None}
        except Exception as e:  # noqa: BLE001 — mapped to wire errors
            return {"error": _err(e)}

    def kv_batch_get(self, req: dict) -> dict:
        busy = self._admit_overload(req, "kv")
        if busy is not None:
            return busy
        resp = self._serve_read("kv_batch_get", req, self._kv_batch_get_local)
        if isinstance(resp, dict) and resp.get("pairs"):
            self._note_read_bytes(req, sum(
                len(p[1]) for p in resp["pairs"] if p and p[1]))
        return resp

    def _kv_batch_get_local(self, req: dict) -> dict:
        try:
            pairs = self.storage.batch_get(req["keys"], req["version"], req.get("context"))
            return {"pairs": [list(p) for p in pairs]}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_scan(self, req: dict) -> dict:
        busy = self._admit_overload(req, "kv")
        if busy is not None:
            return busy
        resp = self._serve_read("kv_scan", req, self._kv_scan_local)
        if isinstance(resp, dict) and resp.get("pairs"):
            self._note_read_bytes(req, sum(
                len(p[0]) + len(p[1]) for p in resp["pairs"] if p and p[1]))
        return resp

    def _kv_scan_local(self, req: dict) -> dict:
        try:
            pairs = self.storage.scan(
                req.get("start_key", b""),
                req.get("end_key"),
                req.get("limit"),
                req["version"],
                req.get("context"),
                reverse=req.get("reverse", False),
                key_only=req.get("key_only", False),
            )
            return {"pairs": [list(p) for p in pairs]}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_prewrite(self, req: dict) -> dict:
        cmd = cmds.Prewrite(
            [_mutation_from_wire(m) for m in req["mutations"]],
            req["primary_lock"],
            req["start_version"],
            lock_ttl=req.get("lock_ttl", 3000),
            use_async_commit=req.get("use_async_commit", False),
            secondaries=req.get("secondaries", []),
            is_pessimistic=req.get("is_pessimistic", False),
            pessimistic_flags=req.get("is_pessimistic_lock", []),
            for_update_ts=req.get("for_update_ts", 0),
        )
        try:
            r = self.storage.sched_txn_command(cmd, req.get("context"))
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}
        if "errors" in r:
            return {"errors": [_err(e) for e in r["errors"]]}
        return {"min_commit_ts": r.get("min_commit_ts", 0)}

    def kv_commit(self, req: dict) -> dict:
        cmd = cmds.Commit(
            [Key.from_raw(k) for k in req["keys"]],
            req["start_version"],
            req["commit_version"],
        )
        try:
            self.storage.sched_txn_command(cmd, req.get("context"))
            self._wake_lock_waiters(req["start_version"])
            return {"commit_version": req["commit_version"]}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_batch_rollback(self, req: dict) -> dict:
        cmd = cmds.Rollback([Key.from_raw(k) for k in req["keys"]], req["start_version"])
        try:
            self.storage.sched_txn_command(cmd, req.get("context"))
            self._wake_lock_waiters(req["start_version"])
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_cleanup(self, req: dict) -> dict:
        cmd = cmds.Cleanup(
            Key.from_raw(req["key"]), req["start_version"], req.get("current_ts", 0)
        )
        try:
            self.storage.sched_txn_command(cmd, req.get("context"))
            self._wake_lock_waiters(req["start_version"])
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_pessimistic_lock(self, req: dict) -> dict:
        """Acquire pessimistic locks; on conflict, WAIT through the lock
        manager (waiter_manager.rs) for up to wait_timeout_ms and retry —
        the reference's WaitForLock flow, with deadlock detection."""
        from .lock_manager import DeadlockError

        def attempt():
            cmd = cmds.AcquirePessimisticLock(
                [(Key.from_raw(k), False) for k in req["keys"]],
                req["primary_lock"],
                req["start_version"],
                req["for_update_ts"],
                lock_ttl=req.get("lock_ttl", 3000),
                return_values=req.get("return_values", False),
            )
            return self.storage.sched_txn_command(cmd, req.get("context"))

        try:
            return {"values": attempt().get("values")}
        except KeyIsLockedError as e:
            wait_ms = req.get("wait_timeout_ms", 0)
            if self.lock_manager is None or not wait_ms:
                return {"error": _err(e)}
            try:
                woken = self.lock_manager.wait_for(
                    req["start_version"], e.lock.ts, e.key, timeout=wait_ms / 1000.0
                )
            except DeadlockError as de:
                return {
                    "error": {
                        "deadlock": {
                            "waiting_txn": de.waiting_txn,
                            "blocked_on_txn": de.blocked_on_txn,
                            "cycle": de.cycle,
                        }
                    }
                }
            if not woken:
                return {"error": _err(e)}  # wait timed out: surface the lock
            try:
                return {"values": attempt().get("values")}
            except Exception as e2:  # noqa: BLE001
                return {"error": _err(e2)}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def deadlock_detect(self, req: dict) -> dict:
        """Detector-leader ingress (the reference's separate Deadlock gRPC
        service, deadlock.rs:343-391): remote stores forward wait-for edges
        here; only the store holding region 1's leadership answers with
        authority."""
        from .lock_manager import DeadlockError, DetectorHandle, FIRST_REGION_ID

        if self.lock_manager is None:
            return {"error": {"other": "lock manager not enabled"}}
        det = self.lock_manager.detector
        if isinstance(det, DetectorHandle):
            router = self.raft_router
            if router is not None and \
                    router.leader_store_of(FIRST_REGION_ID) != router.store_id:
                return {"not_leader": True}
            det = det.local
        tp = req.get("tp")
        try:
            if tp == "detect":
                det.detect(req["waiter_ts"], req["lock_ts"])
            elif tp == "clean_up_wait_for":
                det.clean_up_wait_for(req["waiter_ts"], req["lock_ts"])
            elif tp == "clean_up":
                det.clean_up(req["txn_ts"])
            else:
                return {"error": {"other": f"unknown detect tp {tp!r}"}}
        except DeadlockError as de:
            return {
                "deadlock": {
                    "waiting_txn": de.waiting_txn,
                    "blocked_on_txn": de.blocked_on_txn,
                    "cycle": de.cycle,
                }
            }
        return {"ok": True}

    def _wake_lock_waiters(self, released_ts: int) -> None:
        """Commit/rollback/resolve released this txn's locks: wake waiters
        (scheduler.rs on_release_locks -> lock_mgr.wake_up)."""
        if self.lock_manager is not None:
            self.lock_manager.wake_up_all(released_ts)

    def kv_pessimistic_rollback(self, req: dict) -> dict:
        cmd = cmds.PessimisticRollback(
            [Key.from_raw(k) for k in req["keys"]],
            req["start_version"],
            req["for_update_ts"],
        )
        try:
            self.storage.sched_txn_command(cmd, req.get("context"))
            self._wake_lock_waiters(req["start_version"])
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_txn_heart_beat(self, req: dict) -> dict:
        cmd = cmds.TxnHeartBeat(
            Key.from_raw(req["primary_lock"]), req["start_version"], req["advise_lock_ttl"]
        )
        try:
            r = self.storage.sched_txn_command(cmd, req.get("context"))
            return {"lock_ttl": r["lock_ttl"]}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_check_txn_status(self, req: dict) -> dict:
        cmd = cmds.CheckTxnStatus(
            Key.from_raw(req["primary_key"]),
            req["lock_ts"],
            req.get("caller_start_ts", 0),
            req.get("current_ts", 0),
            rollback_if_not_exist=req.get("rollback_if_not_exist", False),
            force_sync_commit=req.get("force_sync_commit", False),
        )
        try:
            r = self.storage.sched_txn_command(cmd, req.get("context"))
            st = r["status"]
            return {
                "kind": st.kind.value,
                "commit_version": st.commit_ts,
                "lock_ttl": st.lock_ttl,
                "min_commit_ts": st.min_commit_ts,
                "use_async_commit": st.use_async_commit,
            }
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_check_secondary_locks(self, req: dict) -> dict:
        cmd = cmds.CheckSecondaryLocks(
            [Key.from_raw(k) for k in req["keys"]], req["start_version"]
        )
        try:
            r = self.storage.sched_txn_command(cmd, req.get("context"))
            return {
                "locks": [{"ts": l.ts, "primary": l.primary} for l in r["locks"]],
                "commit_ts": r["commit_ts"],
            }
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_scan_lock(self, req: dict) -> dict:
        try:
            locks = self.storage.scan_lock(
                req.get("start_key"), req.get("end_key"), req["max_version"], req.get("limit")
            )
            return {
                "locks": [
                    {"key": k.to_raw(), "primary": l.primary, "lock_version": l.ts, "ttl": l.ttl}
                    for k, l in locks
                ]
            }
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def _raft_store(self):
        st = getattr(self.storage.engine, "store", None)
        if st is None:
            raise RuntimeError("not serving over a raft store")
        return st

    def kv_split_region(self, req: dict) -> dict:
        """Manual region split (kv.rs:710 split_region): allocate ids from
        PD and propose the split admin command on the region leader."""
        if self.pd is None:
            return {"error": {"other": "split_region needs a PD client"}}
        try:
            store = self._raft_store()
            region_id = (req.get("context") or {}).get("region_id")
            peer = store.peers.get(region_id)
            if peer is None or not peer.node.is_leader():
                return {"error": {"not_leader": {"region_id": region_id}}}
            # region boundaries live in ENGINE key space: txn-mode user keys
            # must be memcomparable-encoded first (kv.rs split_region does
            # Key::from_raw for non-raw mode) or the boundary would not sort
            # consistently with the stored keys
            split_key = req["split_key"]
            if not req.get("is_raw_kv", False):
                split_key = Key.from_raw(split_key).encoded
            if not peer.region.contains(split_key) or split_key == peer.region.start_key:
                return {"error": {"other": "split key out of region range"}}
            new_region_id = self.pd.alloc_id()
            new_pids = [self.pd.alloc_id() for _ in peer.region.peers]
            done = threading.Event()
            res: list = []
            peer.propose_split(
                split_key, new_region_id, new_pids,
                lambda r: (res.append(r), done.set()),
            )
            if not done.wait(5):
                return {"error": {"other": "split timed out"}}
            if isinstance(res[0], Exception):
                return {"error": _err(res[0])}
            return {"new_region_id": new_region_id}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_read_index(self, req: dict) -> dict:
        """Linearizable read barrier (kv.rs:796 read_index): returns once a
        quorum confirms leadership; callers may then read locally."""
        try:
            store = self._raft_store()
            region_id = (req.get("context") or {}).get("region_id") or req.get("region_id")
            peer = store.peers.get(region_id)
            if peer is None or not peer.node.is_leader():
                return {"error": {"not_leader": {"region_id": region_id}}}
            done = threading.Event()
            err: list = []
            peer.read_index(lambda e: (err.append(e) if e is not None else None, done.set()))
            if not done.wait(5):
                return {"error": {"other": "read_index timed out"}}
            if err:
                return {"error": _err(err[0])}
            return {"read_index": peer.node.commit}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_check_leader(self, req: dict) -> dict:
        """Leadership confirmation for resolved-ts advance (kv.rs:1005
        check_leader): of the requested regions, which does this store lead?"""
        try:
            store = self._raft_store()
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}
        leading = []
        for rid in req.get("regions", []):
            peer = store.peers.get(rid)
            if peer is not None and peer.node.is_leader():
                leading.append(rid)
        return {"regions": leading}

    def kv_flashback_to_version(self, req: dict) -> dict:
        """FlashbackToVersion (kvproto kvrpcpb.FlashbackToVersionRequest)."""
        cmd = cmds.FlashbackToVersion(
            version=req["version"],
            start_ts=req["start_ts"],
            commit_ts=req["commit_ts"],
            start_key=Key.from_raw(req["start_key"]) if req.get("start_key") else None,
            end_key=Key.from_raw(req["end_key"]) if req.get("end_key") else None,
        )
        try:
            return self.storage.sched_txn_command(cmd, req.get("context"))
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_resolve_lock(self, req: dict) -> dict:
        cmd = cmds.ResolveLock(
            req["start_version"],
            req.get("commit_version", 0),
            [Key.from_raw(k) for k in req["keys"]] if req.get("keys") else None,
        )
        try:
            r = self.storage.sched_txn_command(cmd, req.get("context"))
            self._wake_lock_waiters(req["start_version"])
            return {"resolved": r["resolved"]}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def kv_delete_range(self, req: dict) -> dict:
        from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE, WriteBatch
        from ..storage.txn_types import Key as K

        wb = WriteBatch()
        start = K.from_raw(req["start_key"]).encoded
        end = K.from_raw(req["end_key"]).encoded
        for cf in (CF_DEFAULT, CF_LOCK, CF_WRITE):
            wb.delete_range_cf(cf, start, end)
        try:
            self.storage.engine.write(req.get("context"), wb)
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    # -- raw KV -------------------------------------------------------------

    def raw_get(self, req: dict) -> dict:
        v = self.storage.raw_get(req["key"], req.get("context"))
        return {"value": v, "not_found": v is None}

    def raw_batch_get(self, req: dict) -> dict:
        return {"pairs": [list(p) for p in self.storage.raw_batch_get(req["keys"], req.get("context"))]}

    def raw_put(self, req: dict) -> dict:
        self.storage.raw_put(req["key"], req["value"], req.get("context"), ttl=req.get("ttl", 0))
        return {}

    def raw_batch_put(self, req: dict) -> dict:
        self.storage.raw_batch_put(
            [tuple(p) for p in req["pairs"]], req.get("context"), ttl=req.get("ttl", 0)
        )
        return {}

    def raw_delete(self, req: dict) -> dict:
        self.storage.raw_delete(req["key"], req.get("context"))
        return {}

    def raw_batch_delete(self, req: dict) -> dict:
        self.storage.raw_batch_delete(req["keys"], req.get("context"))
        return {}

    def raw_delete_range(self, req: dict) -> dict:
        self.storage.raw_delete_range(req["start_key"], req["end_key"], req.get("context"))
        return {}

    def raw_scan(self, req: dict) -> dict:
        pairs = self.storage.raw_scan(
            req.get("start_key", b""),
            req.get("end_key"),
            req.get("limit"),
            req.get("context"),
            reverse=req.get("reverse", False),
            key_only=req.get("key_only", False),
        )
        return {"kvs": [list(p) for p in pairs]}

    def raw_batch_scan(self, req: dict) -> dict:
        """Multiple ranges, each capped at each_limit (kv.rs raw_batch_scan)."""
        out = []
        for rng in req["ranges"]:
            start, end = rng[0], rng[1]
            pairs = self.storage.raw_scan(
                start,
                end if end else None,
                req.get("each_limit"),
                req.get("context"),
                reverse=req.get("reverse", False),
                key_only=req.get("key_only", False),
            )
            out.extend(list(p) for p in pairs)
        return {"kvs": out}

    def raw_get_key_ttl(self, req: dict) -> dict:
        ttl = self.storage.raw_get_key_ttl(req["key"], req.get("context"))
        return {"ttl": ttl, "not_found": ttl is None}

    def raw_compare_and_swap(self, req: dict) -> dict:
        ok, prev = self.storage.raw_compare_and_swap(
            req["key"], req.get("previous_value"), req["value"], req.get("context"),
            ttl=req.get("ttl", 0),
        )
        return {"succeed": ok, "previous_value": prev}

    # -- coprocessor --------------------------------------------------------

    # -- MVCC debug reads (kv.rs:229-240, debug.rs mvcc_by_key) --------------

    def _mvcc_info_for_key(self, snap, raw_key: bytes) -> dict:
        from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE
        from ..storage.txn_types import Key as K, Lock, Write, split_ts

        key = K.from_raw(raw_key)
        info: dict = {"lock": None, "writes": [], "values": []}
        raw_lock = snap.get_cf(CF_LOCK, key.encoded)
        if raw_lock is not None:
            lock = Lock.from_bytes(raw_lock)
            info["lock"] = {
                "type": lock.lock_type.name,
                "start_ts": lock.ts,
                "primary": lock.primary,
                "ttl": lock.ttl,
                "short_value": lock.short_value,
            }
        hi = key.append_ts(2**64 - 1).encoded
        # bounded to this key's version run: ts 0 sorts last under the desc
        # ts encoding, so the exclusive end is just past it
        lo_excl = key.append_ts(0).encoded + b"\x00"
        for k, v in snap.scan_cf(CF_WRITE, hi, lo_excl):
            try:
                user, commit_ts = split_ts(k)
            except ValueError:
                continue  # unversioned neighbor (raw-KV key) interleaved in the run
            if user != key.encoded:
                break
            w = Write.from_bytes(v)
            info["writes"].append(
                {
                    "type": w.write_type.name,
                    "start_ts": w.start_ts,
                    "commit_ts": commit_ts,
                    "short_value": w.short_value,
                }
            )
        for k, v in snap.scan_cf(CF_DEFAULT, hi, lo_excl):
            try:
                user, start_ts = split_ts(k)
            except ValueError:
                continue  # unversioned neighbor (raw-KV key)
            if user != key.encoded:
                break
            info["values"].append({"start_ts": start_ts, "value": v})
        return info

    def mvcc_get_by_key(self, req: dict) -> dict:
        """Every MVCC trace of one key: lock, write versions, large values
        (kv.rs:229 mvcc_get_by_key)."""
        try:
            snap = self.storage.engine.snapshot(req.get("context"))
            return {"key": req["key"], "info": self._mvcc_info_for_key(snap, req["key"])}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def mvcc_get_by_start_ts(self, req: dict) -> dict:
        """Find the key a txn (start_ts) touched, then its MVCC info
        (kv.rs:235 mvcc_get_by_start_ts) — scans CF_WRITE + CF_LOCK for the
        first trace of the txn inside the requested region/range."""
        from ..storage.engine import CF_LOCK, CF_WRITE
        from ..storage.txn_types import Key as K, Lock, Write, split_ts

        start_ts = req["start_ts"]
        try:
            snap = self.storage.engine.snapshot(req.get("context"))
            found: bytes | None = None
            for k, v in snap.scan_cf(CF_WRITE, b"", None):
                user, _commit = split_ts(k)
                if Write.from_bytes(v).start_ts == start_ts:
                    found = K.from_encoded(user).to_raw()
                    break
            if found is None:
                for k, v in snap.scan_cf(CF_LOCK, b"", None):
                    if Lock.from_bytes(v).ts == start_ts:
                        found = K.from_encoded(k).to_raw()
                        break
            if found is None:
                return {"key": None, "info": None}
            return {"key": found, "info": self._mvcc_info_for_key(snap, found)}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    # -- GC support (kv.rs:349-525) ------------------------------------------

    def kv_gc(self, req: dict) -> dict:
        """Deliberate stub, like the reference (kv.rs:349 returns
        unimplemented): GC is driven by the PD safe point through the
        GcManager loop, never by a client RPC."""
        return {"error": {"other": "kv_gc is deprecated: GC is safe-point driven (gc_manager)"}}

    def _gc(self):
        if self.gc_worker is None:
            raise RuntimeError("gc worker not enabled on this node")
        return self.gc_worker

    def unsafe_destroy_range(self, req: dict) -> dict:
        try:
            self._gc().unsafe_destroy_range(req["start_key"], req["end_key"], req.get("context"))
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def physical_scan_lock(self, req: dict) -> dict:
        try:
            locks = self._gc().physical_scan_lock(
                req["max_ts"], req.get("start_key"), req.get("limit")
            )
            return {
                "locks": [
                    {"key": k, "lock_ts": lock.ts, "primary": lock.primary, "ttl": lock.ttl}
                    for k, lock in locks
                ]
            }
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def register_lock_observer(self, req: dict) -> dict:
        try:
            self._gc().register_lock_observer(req["max_ts"])
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def check_lock_observer(self, req: dict) -> dict:
        try:
            return self._gc().check_lock_observer()
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def remove_lock_observer(self, req: dict) -> dict:
        try:
            self._gc().remove_lock_observer()
            return {}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    # -- cluster status RPCs (kv.rs:1034,1061) -------------------------------

    def get_store_safe_ts(self, req: dict) -> dict:
        """Minimum resolved-ts across this store's regions: the floor below
        which any stale read on this store is safe (kv.rs:1034).  Uses the
        RegionReadProgress view (safe_ts) so FOLLOWER stores — whose local
        resolvers never advance — report the disseminated floor instead of
        a frozen 0."""
        if self.resolved_ts is None:
            return {"safe_ts": 0}
        return {"safe_ts": self.resolved_ts.safe_ts()}

    def debug_read_progress(self, req: dict) -> dict:
        """Per-region RegionReadProgress pairs + the store safe_ts: the
        stuck-follower debugging surface (ctl.py ``read-progress`` and the
        status server's ``/debug/read_progress``).  Optional ``region_id``
        narrows to one region."""
        if self.resolved_ts is None:
            return {"safe_ts": 0, "regions": {}}
        rid = req.get("region_id")
        snap = self.resolved_ts.progress_snapshot()
        if rid is not None:
            resolved, required = self.resolved_ts.progress_of(rid)
            snap = {rid: (resolved, required)}
        return {
            "safe_ts": self.resolved_ts.safe_ts(),
            "regions": {
                r: {"resolved_ts": pair[0], "required_apply_index": pair[1]}
                for r, pair in sorted(snap.items())
            },
        }

    def debug_device_owners(self, req: dict) -> dict:
        """This store's current view of device-owner placement (region ->
        store), as advertised through PD (docs/wire_path.md)."""
        rp = self.read_plane
        return {"owners": rp.device_owners() if rp is not None else {}}

    def debug_wire_stages(self, req: dict) -> dict:
        """Per-stage wire-path summary (tikv_wire_stage_seconds): count and
        accumulated seconds for decode/route/execute/encode — the RPC the
        cluster bench scrapes to report where the wire path spends its time
        (docs/wire_path.md)."""
        from .server import WIRE_STAGE

        stages = {}
        for labels in WIRE_STAGE.label_sets():
            stage = labels.get("stage")
            if stage is None:
                continue
            stages[stage] = {
                "count": WIRE_STAGE.count(stage=stage),
                "seconds": WIRE_STAGE.total(stage=stage),
            }
        return {"stages": stages}

    def debug_observatory(self, req: dict) -> dict:
        """Performance-observatory state (docs/observatory.md; ``ctl.py
        observatory`` and the status server's ``/debug/observatory``):
        per-plan-signature path cost profiles, the compile ledger, and the
        pinned-HBM watermarks.  ``sig`` narrows to one signature; ``top``
        returns the time-spent leaderboard instead of the full snapshot;
        ``floor`` returns the per-sig rows/s baselines obs_diff.py gates
        on."""
        from ..copr import observatory as obs

        if req.get("top"):
            return {"top": obs.OBSERVATORY.top(int(req.get("limit", 20)))}
        if req.get("floor"):
            return obs.OBSERVATORY.floor(
                min_count=int(req.get("min_count", 3)))
        return obs.OBSERVATORY.snapshot(sig=req.get("sig"))

    def debug_overload(self, req: dict) -> dict:
        """Overload-control state (docs/robustness.md "Overload"; ``ctl.py
        overload`` and the status server's ``/debug/overload``): per-tenant
        bucket levels + effective rates, shed/defer counts, the adaptive
        controller's scale and evidence, and HBM partition occupancy."""
        ov = self.overload
        if ov is None and self.copr is not None:
            ov = self.copr.overload
        if ov is None:
            return {"enabled": False, "wired": False}
        return ov.snapshot()

    def debug_cost_router(self, req: dict) -> dict:
        """Cost-router + geometry-tuner state (docs/cost_router.md;
        ``ctl.py cost-router`` and the status server's
        ``/debug/cost_router``): decision counts by reason, the recent
        decision ring, and the tuner's knobs / in-flight change /
        keep-revert history."""
        if self.copr is None:
            return {"enabled": False, "wired": False}
        return self.copr.cost_router_snapshot()

    def debug_traces(self, req: dict) -> dict:
        """Recent + slow traces from the process tracer (docs/tracing.md):
        the ``ctl.py trace`` surface.  ``trace_id`` narrows to one trace;
        ``limit`` bounds the rings returned."""
        from ..util import trace

        tid = req.get("trace_id")
        if tid:
            t = trace.TRACER.get(tid)
            if t is None:
                return {"error": {"other": f"trace {tid!r} not found"}}
            return {"trace": t, "timeline": trace.timeline(t)}
        return trace.snapshot(limit=int(req.get("limit", 20)))

    def get_lock_wait_info(self, req: dict) -> dict:
        """Current pessimistic lock waits (kv.rs:1061): who waits on whom."""
        if self.lock_manager is None:
            return {"entries": []}
        waiters = self.lock_manager.wait_info()
        return {
            "entries": [
                {"key": w["key"], "txn": w["start_ts"], "wait_for_txn": w["lock_ts"]}
                for w in waiters
            ]
        }

    # -- Backup service (backup/src/service.rs, server.rs:955-984) -----------

    def backup(self, req: dict) -> dict:
        """Run a consistent backup of the requested ranges at backup_ts into
        the external storage named by a URL (local:///, s3://, gcs://...),
        one file per range."""
        from ..sidecar.backup import BackupEndpoint
        from ..sidecar.cloud import create_storage

        try:
            storage = create_storage(req["storage"])
            ep = BackupEndpoint(storage)
            snap = self.storage.engine.snapshot(req.get("context"))
            files = []
            for i, rng in enumerate(req["ranges"]):
                start, end = rng[0], rng[1]
                name = req.get("name_prefix", "backup") + f"-{i:04d}"
                files.append(
                    ep.backup_range(snap, name, req["backup_ts"], start or None, end or None)
                )
            return {"files": files}
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    # -- Diagnostics service (service/diagnostics/, server.rs:907) -----------

    def _diag(self):
        if self.diagnostics is None:
            from .diagnostics import Diagnostics

            self.diagnostics = Diagnostics()
        return self.diagnostics

    def diagnostics_search_log(self, req: dict) -> dict:
        return {
            "lines": self._diag().search_log(
                patterns=req.get("patterns"),
                levels=req.get("levels"),
                start_time=req.get("start_time"),
                end_time=req.get("end_time"),
                limit=req.get("limit", 1024),
            )
        }

    def diagnostics_server_info(self, req: dict) -> dict:
        return self._diag().server_info()

    def _parse_dag_wire(self, dag: dict):
        """Memoized wire-dict -> DagRequest parse (shared by the unary,
        batch, and streaming handlers).

        The key is the plan's canonical wire bytes, which INCLUDE
        ``encode_type`` (dag_wire emits it whenever non-default): a datum
        and a TypeChunk request with identical executor bytes parse to
        distinct DagRequest objects, so a cached parse can never pin the
        wrong response encoder onto the other encoding's requests
        (tests/test_chunk_wire.py)."""
        from . import wire
        from ..copr.dag_wire import dag_from_wire

        key = wire.dumps(dag)
        with self._dag_memo_mu:
            parsed = self._dag_memo.get(key)
        if parsed is None:
            parsed = dag_from_wire(dag)
            with self._dag_memo_mu:
                self._dag_memo[key] = parsed
                while len(self._dag_memo) > 128:
                    self._dag_memo.pop(next(iter(self._dag_memo)))
        return parsed

    def _parse_copr_request(self, req: dict) -> CoprRequest:
        """ONE definition of the coprocessor sub-request parse (unary and
        batch must accept identical payloads — including dag-less CHECKSUM)."""
        dag = req.get("dag")
        if isinstance(dag, dict):
            dag = self._parse_dag_wire(dag)
        tp = req.get("tp", REQ_TYPE_DAG)
        if dag is None and tp != REQ_TYPE_CHECKSUM:
            raise ValueError("dag required for this request type")
        context = req.get("context") or {}
        if "timeout_ms" in context and "deadline" not in context:
            # wire clients can't share our monotonic clock: their relative
            # budget becomes an absolute deadline HERE, at parse time, so
            # queue wait and execution all draw down the same budget
            # (util.retry.deadline_from_context; the scheduler lanes shed
            # expired work before dispatch)
            from ..util.retry import deadline_from_context

            context = dict(context)
            context["deadline"] = deadline_from_context(context)
        return CoprRequest(
            tp=tp,
            dag=dag,
            ranges=[tuple(r) for r in req["ranges"]],
            start_ts=req["start_ts"],
            context=context,
        )

    def coprocessor(self, req: dict) -> dict:
        """req: {tp, dag (DagRequest in-process, or wire dict; optional for
        CHECKSUM), ranges, start_ts}.

        When the endpoint's read scheduler runs in continuous mode, unary
        requests route through it: concurrent clients' device-eligible DAGs
        coalesce into cross-region micro-batches (scheduler.py), each thread
        blocking only until the batch that carries its request completes —
        the unified-read-pool serving shape with XLA dispatches as the
        shared resource.  With the scheduler stopped (the default), this is
        the plain per-request path.

        Routed through the read-degradation ladder: a DAG for a region this
        store does not lead forwards one hop, then degrades to a follower
        stale serve off the warm region column cache when the context
        permits (docs/stale_reads.md).

        Device-owner routing (docs/wire_path.md): a device-eligible DAG
        whose region image is warm on ANOTHER store's cache forwards one
        hop to that store instead of serving a cold local fallback —
        placement advertised through PD, loop-guarded, breaker-protected."""
        busy = self._admit_overload(req, "copr")
        if busy is not None:
            return busy
        fwd = self._try_owner_forward(req)
        if fwd is not None:
            return fwd
        return self._serve_read("coprocessor", req, self._coprocessor_local)

    def _try_owner_forward(self, req: dict) -> dict | None:
        """The owner-routing gate: forward only when (1) the request has not
        already hopped, (2) PD names another store as the region's warm
        device owner, (3) this store cannot serve the region warm itself,
        and (4) the plan is device-eligible — otherwise local serving is
        already the best this cluster can do."""
        rp = self.read_plane
        if rp is None or self.copr is None:
            return None
        ctx = req.get("context") or {}
        if ctx.get("forwarded"):
            return None
        region_id = ctx.get("region_id")
        if region_id is None:
            return None
        owner = rp.device_owner_of(region_id)
        if owner is None or owner == rp.store_id:
            return None
        rc = getattr(self.copr, "region_cache", None)
        if (self.copr.device_enabled() and rc is not None
                and rc.has_warm_region(region_id)):
            return None  # warm here: a hop can only add latency
        if not self._dag_device_eligible(req.get("dag")):
            return None
        return rp.forward_device_owner("coprocessor", req, owner)

    def _dag_device_eligible(self, dag) -> bool:
        """Cheap, memoized device-eligibility probe for owner routing —
        deliberately independent of THIS store's enable_device switch (a
        CPU-only store is exactly the one that benefits from forwarding)."""
        from ..copr import jax_eval
        from ..copr.dag import Aggregation

        if isinstance(dag, dict):
            try:
                dag = self._parse_dag_wire(dag)
            except Exception:  # noqa: BLE001 — malformed plans serve locally
                return False
        if dag is None:
            return False
        key = id(dag)
        with self._dag_memo_mu:
            hit = self._dag_eligible_memo.get(key)
        if hit is not None and hit[0] is dag:
            return hit[1]
        ok = (any(isinstance(e, Aggregation) for e in dag.executors)
              and jax_eval.supports(dag))
        with self._dag_memo_mu:
            self._dag_eligible_memo[key] = (dag, ok)
            while len(self._dag_eligible_memo) > 256:
                self._dag_eligible_memo.pop(
                    next(iter(self._dag_eligible_memo)))
        return ok

    @staticmethod
    def _requested_chunk(req: dict) -> bool:
        """Did THIS wire request opt into TypeChunk?  (The parsed dag may
        already be the downgraded datum twin, so read the raw request.)"""
        dag = req.get("dag") if isinstance(req, dict) else None
        if isinstance(dag, dict):
            return dag.get("encode_type", 0) == 1
        return getattr(dag, "encode_type", 0) == 1

    @staticmethod
    def _copr_resp_dict(r, requested_chunk: bool, declined: bool) -> dict:
        """One coprocessor sub-response as a wire dict.  TypeChunk
        responses ship ``data_parts`` — the unjoined column slabs, each
        ≥PASSTHROUGH_MIN riding the frame as its own memoryview part
        through the ``sendmsg`` gather write — plus ``encode_type`` so the
        client picks the decoder.  Outcomes land in
        ``tikv_wire_chunk_total`` (declines were counted, with their cause,
        at negotiation time)."""
        out: dict = {"from_device": r.from_device}
        if r.encode_type:
            out["encode_type"] = r.encode_type
            out["data_parts"] = (r.data_parts if r.data_parts is not None
                                 else [r.data])
            outcome = "chunk"
        else:
            out["data"] = r.data
            outcome = None if (not requested_chunk or declined) \
                else "datum_fallback"
        if requested_chunk and outcome is not None:
            from ..util.metrics import REGISTRY

            REGISTRY.counter(
                "tikv_wire_chunk_total",
                "TypeChunk response negotiation, by outcome (cause on "
                "declines)",
            ).inc(outcome=outcome, cause="")
        return out

    @staticmethod
    def _copr_resp_nbytes(r) -> int:
        """Response payload size WITHOUT forcing the lazy data_parts join
        (the zero-copy wire path's whole point)."""
        if r.data_parts is not None:
            return sum(p.nbytes if isinstance(p, memoryview) else len(p)
                       for p in r.data_parts)
        return len(r.data)

    def _coprocessor_local(self, req: dict) -> dict:
        assert self.copr is not None, "coprocessor endpoint not wired"
        try:
            creq = self._parse_copr_request(req)
            sched = getattr(self.copr, "scheduler", None)
            if sched is not None and sched.running:
                r = sched.execute(creq)
            else:
                r = self.copr.handle_request(creq)
            self._note_read_bytes(req, self._copr_resp_nbytes(r))
            return self._copr_resp_dict(
                r, self._requested_chunk(req),
                bool((creq.context or {}).get("chunk_declined")))
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

    def coprocessor_batch(self, req: dict) -> dict:
        """K coprocessor requests in one RPC (batch_coprocessor surface):
        device-eligible aggregations over the same region view fuse into ONE
        device program; everything else answers per-request.  Response order
        matches request order; a bad sub-request fails ONLY its own slot."""
        assert self.copr is not None, "coprocessor endpoint not wired"
        from ..util.retry import DeadlineExceeded

        subs = req.get("requests") or []
        # quota admission per sub-request at the WIRE BOUNDARY (no defer —
        # a synchronous batch must not sleep per rider): client-supplied
        # markers stripped, missing contexts materialized, over-quota slots
        # answer ServerIsBusy typed with the refill-deficit hint while
        # siblings serve normally
        out_by_idx: dict[int, dict] = {}
        if self.overload is not None:
            for i, sub in enumerate(subs):
                ctx = sub.get("context")
                if not isinstance(ctx, dict):
                    ctx = sub["context"] = {}
                ctx.pop("_overload_admitted", None)
                try:
                    self.overload.admit(ctx, where="batch", wait=False)
                except Exception as e:  # noqa: BLE001 — ServerBusyError
                    out_by_idx[i] = {"error": _err(e)}
            if out_by_idx:
                live = [(i, sub) for i, sub in enumerate(subs)
                        if i not in out_by_idx]
                try:
                    creqs = [self._parse_copr_request(s) for _i, s in live]
                    results, errors = self.copr.handle_batch_errors(creqs)
                except Exception:  # noqa: BLE001 — parse poisons nothing
                    merged = [out_by_idx.get(i) or self.coprocessor(sub)
                              for i, sub in enumerate(subs)]
                    return {"responses": merged}
                served = {}
                for (i, sub), r, e, creq in zip(live, results, errors, creqs):
                    if e is None and r is not None:
                        served[i] = self._copr_resp_dict(
                            r, self._requested_chunk(sub),
                            bool((creq.context or {}).get("chunk_declined")))
                    elif isinstance(e, DeadlineExceeded):
                        served[i] = {"error": _err(e)}
                    else:
                        served[i] = self.coprocessor(sub)
                return {"responses": [out_by_idx.get(i) or served[i]
                                      for i in range(len(subs))]}
        try:
            creqs = [self._parse_copr_request(sub) for sub in subs]
            results, errors = self.copr.handle_batch_errors(creqs)
        except Exception:  # noqa: BLE001 — a parse failure poisons nothing
            return {"responses": [self.coprocessor(sub) for sub in subs]}
        out = []
        for sub, r, e, creq in zip(subs, results, errors, creqs):
            if e is None and r is not None:
                # per-region payloads (chunk or datum) answer in THIS one
                # frame — the scheduler's vmapped cross-region batch rides
                # back to the wire client as a single multi-response frame
                # with per-region error isolation (docs/wire_path.md)
                out.append(self._copr_resp_dict(
                    r, self._requested_chunk(sub),
                    bool((creq.context or {}).get("chunk_declined"))))
            elif isinstance(e, DeadlineExceeded):
                # expired in queue: report it, never re-dispatch — the
                # client already gave up on this slot
                out.append({"error": _err(e)})
            else:
                # per-slot re-serve keeps the old isolation contract (and a
                # batch-path device error may still succeed per-request);
                # handle_request's entry gate sheds it cheaply if its
                # deadline lapsed meanwhile
                out.append(self.coprocessor(sub))
        return {"responses": out}

    def coprocessor_stream(self, req: dict):
        """Streamed DAG execution (endpoint.rs:508-584): returns a GENERATOR
        of per-frame dicts.  The server writes each frame to the wire as it
        is produced (same req_id, terminated by a stream_end frame), so
        server-side memory stays O(one frame) and a slow client back-
        pressures the executor through TCP instead of ballooning a buffer.
        Validation errors before the first frame return a plain error dict
        (the unary shape)."""
        assert self.copr is not None, "coprocessor endpoint not wired"
        busy = self._admit_overload(req, "stream")
        if busy is not None:
            return busy
        try:
            dag = req.get("dag")
            if isinstance(dag, dict):
                dag = self._parse_dag_wire(dag)
            if dag is None:
                return {"error": {"other": "dag required"}}
            creq = CoprRequest(
                tp=req.get("tp", REQ_TYPE_DAG),
                dag=dag,
                ranges=[tuple(r) for r in req["ranges"]],
                start_ts=req["start_ts"],
                context=req.get("context") or {},
            )
            rows_per_stream = req.get("rows_per_stream", 1024)
        except Exception as e:  # noqa: BLE001
            return {"error": _err(e)}

        requested_chunk = self._requested_chunk(req)

        def frames():
            for r in self.copr.handle_streaming_request(creq, rows_per_stream):
                yield self._copr_resp_dict(
                    r, requested_chunk,
                    bool((creq.context or {}).get("chunk_declined")))

        return frames()
