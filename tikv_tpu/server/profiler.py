"""Profiling surface: CPU profiles and heap snapshots on demand.

Re-expression of the reference's pprof endpoints
(``src/server/status_server/profile.rs`` — /debug/pprof/profile samples CPU
for ``seconds`` and streams a report; /debug/pprof/heap dumps allocator
stats).  The tpu-native equivalents build on the runtimes we actually have:

* CPU: a **statistical wall-clock sampler over every thread** —
  ``sys._current_frames()`` polled at ~100Hz, stacks aggregated like pprof's
  sample profiles (the reference's pprof-rs works the same way via SIGPROF).
  A deterministic tracer (cProfile) would only see the calling thread;
  request handling lives on pool threads, so sampling is the correct shape.
* Heap: ``tracemalloc`` top allocation sites grouped by file:line.

Both are pull-based and cost nothing while idle — profiling only runs inside
an explicit window, matching the reference's activate/deactivate model.
"""

from __future__ import annotations

import sys
import threading
import time
import tracemalloc
from collections import Counter


class Profiler:
    _mu = threading.Lock()  # one profile window at a time, process-wide

    def cpu_profile(self, seconds: float = 1.0, hz: int = 100, raw: bool = False) -> bytes:
        """Sample all threads for ``seconds``; returns a report.

        ``raw=True`` returns collapsed stacks (``frame;frame;frame count``
        per line — feed straight to a flamegraph renderer); otherwise a
        self-time table per function.
        """
        if not Profiler._mu.acquire(blocking=False):
            raise RuntimeError("another profile window is active")
        try:
            me = threading.get_ident()
            stacks: Counter = Counter()
            leaf: Counter = Counter()
            interval = 1.0 / max(1, hz)
            deadline = time.monotonic() + max(0.0, seconds)
            n_samples = 0
            while time.monotonic() < deadline:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue  # the sampler's own wait loop is noise
                    parts = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        parts.append(f"{code.co_filename}:{code.co_name}")
                        f = f.f_back
                    if not parts:
                        continue
                    stacks[";".join(reversed(parts))] += 1
                    leaf[parts[0]] += 1
                n_samples += 1
                time.sleep(interval)
            if raw:
                lines = [f"{stack} {n}" for stack, n in stacks.most_common()]
                return ("\n".join(lines) + "\n").encode()
            out = [
                f"cpu profile: {n_samples} sampling rounds over "
                f"{seconds:.2f}s at {hz}Hz (all threads)",
                f"{'samples':>10}  location",
            ]
            for loc, n in leaf.most_common(50):
                out.append(f"{n:>10}  {loc}")
            return ("\n".join(out) + "\n").encode()
        finally:
            Profiler._mu.release()

    def heap_profile(self, top: int = 50) -> bytes:
        """Top allocation sites by live bytes (tracemalloc window)."""
        with Profiler._mu:  # start/snapshot/stop must not interleave
            started_here = not tracemalloc.is_tracing()
            if started_here:
                tracemalloc.start()
                # let in-flight work allocate so the snapshot isn't empty
                # lint: allow(lock-blocking-call) -- _mu IS the one-profile-
                # window-at-a-time gate; sleeping inside it is the feature
                time.sleep(0.1)
            try:
                snap = tracemalloc.take_snapshot()
            finally:
                if started_here:
                    tracemalloc.stop()
        lines = []
        total = 0
        for stat in snap.statistics("lineno")[:top]:
            frame = stat.traceback[0]
            lines.append(
                f"{stat.size:>12d} B {stat.count:>8d} objs  {frame.filename}:{frame.lineno}"
            )
            total += stat.size
        header = f"heap profile: top {len(lines)} sites, {total} B shown\n"
        return (header + "\n".join(lines) + "\n").encode()
