"""Profiling surface: CPU profiles and heap snapshots on demand.

Re-expression of the reference's pprof endpoints
(``src/server/status_server/profile.rs`` — /debug/pprof/profile samples CPU
for ``seconds`` and streams a report; /debug/pprof/heap dumps allocator
stats).  The tpu-native equivalents build on the runtimes we actually have:

* CPU: ``cProfile`` across all request handling for the window, rendered as
  the classic cumulative-time table (callgrind/flamegraph-ready raw stats
  available via ``pstats``-format bytes).
* Heap: ``tracemalloc`` top allocation sites grouped by file:line.

Both are pull-based and allocation-free when idle — profiling only costs
while a request is in flight, matching the reference's activate/deactivate
window model.
"""

from __future__ import annotations

import cProfile
import io
import marshal
import pstats
import threading
import time
import tracemalloc


class Profiler:
    _mu = threading.Lock()  # one profile window at a time, process-wide

    def cpu_profile(self, seconds: float = 1.0, sort: str = "cumulative", raw: bool = False) -> bytes:
        """Sample CPU for ``seconds`` and return a report.

        ``raw=True`` returns marshalled pstats (loadable by
        ``pstats.Stats``/snakeviz); otherwise a text table.
        """
        if not Profiler._mu.acquire(blocking=False):
            raise RuntimeError("another profile window is active")
        try:
            prof = cProfile.Profile()
            prof.enable()
            time.sleep(max(0.0, seconds))
            prof.disable()
            if raw:
                prof.snapshot_stats()
                return marshal.dumps(prof.stats)
            out = io.StringIO()
            pstats.Stats(prof, stream=out).sort_stats(sort).print_stats(50)
            return out.getvalue().encode()
        finally:
            Profiler._mu.release()

    def heap_profile(self, top: int = 50) -> bytes:
        """Top allocation sites by live bytes (tracemalloc window)."""
        started_here = not tracemalloc.is_tracing()
        if started_here:
            tracemalloc.start()
            # let in-flight work allocate so the snapshot isn't empty
            time.sleep(0.1)
        try:
            snap = tracemalloc.take_snapshot()
        finally:
            if started_here:
                tracemalloc.stop()
        lines = []
        total = 0
        for stat in snap.statistics("lineno")[:top]:
            frame = stat.traceback[0]
            lines.append(
                f"{stat.size:>12d} B {stat.count:>8d} objs  {frame.filename}:{frame.lineno}"
            )
            total += stat.size
        header = f"heap profile: top {len(lines)} sites, {total} B shown\n"
        return (header + "\n".join(lines) + "\n").encode()
