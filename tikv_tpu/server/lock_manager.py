"""Pessimistic lock waiting + distributed deadlock detection.

Re-expression of ``src/server/lock_manager`` (waiter_manager.rs wait queues
with timeouts; deadlock.rs detector).  Waiters blocked on a lock register in
per-key queues; releases (commit/rollback) wake them in order.  The deadlock
detector maintains the waits-for graph (txn → txn) and rejects an edge that
would close a cycle, reporting the cycle's hash like the reference's
``deadlock_key_hash``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class DeadlockError(Exception):
    def __init__(self, waiting_txn: int, blocked_on_txn: int, cycle: list[int]):
        self.waiting_txn = waiting_txn
        self.blocked_on_txn = blocked_on_txn
        self.cycle = cycle
        super().__init__(f"deadlock: txn {waiting_txn} → {blocked_on_txn} closes cycle {cycle}")


class DeadlockDetector:
    """Waits-for graph with cycle check on edge insertion (deadlock.rs).

    In the reference this is a cluster-wide service hosted by region 1's
    leader; here it is a store-local authority with the same API, callable
    over the service layer for remote stores.
    """

    def __init__(self):
        self._mu = threading.Lock()
        # waits_for[a] = set of txns a waits on
        self.waits_for: dict[int, set[int]] = {}

    def detect(self, waiter_ts: int, lock_ts: int) -> None:
        """Register edge waiter→lock; raise DeadlockError if it closes a cycle."""
        with self._mu:
            cycle = self._path(lock_ts, waiter_ts)
            if cycle is not None:
                raise DeadlockError(waiter_ts, lock_ts, cycle + [waiter_ts])
            self.waits_for.setdefault(waiter_ts, set()).add(lock_ts)

    def _path(self, frm: int, to: int) -> list[int] | None:
        seen = set()
        stack = [(frm, [frm])]
        while stack:
            node, path = stack.pop()
            if node == to:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.waits_for.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def clean_up_wait_for(self, waiter_ts: int, lock_ts: int) -> None:
        with self._mu:
            edges = self.waits_for.get(waiter_ts)
            if edges is not None:
                edges.discard(lock_ts)
                if not edges:
                    del self.waits_for[waiter_ts]

    def clean_up(self, txn_ts: int) -> None:
        with self._mu:
            self.waits_for.pop(txn_ts, None)


@dataclass
class Waiter:
    start_ts: int
    lock_ts: int
    key: bytes
    event: threading.Event = field(default_factory=threading.Event)
    timed_out: bool = False


class WaiterManager:
    """Per-key wait queues with timeouts (waiter_manager.rs)."""

    def __init__(self, detector: DeadlockDetector | None = None, default_timeout: float = 3.0):
        self.detector = detector or DeadlockDetector()
        self.default_timeout = default_timeout
        self._mu = threading.Lock()
        self._queues: dict[bytes, list[Waiter]] = {}

    def wait_for(self, start_ts: int, lock_ts: int, key: bytes, timeout: float | None = None) -> bool:
        """Block until the lock on ``key`` is released.  Returns False on
        timeout.  Raises DeadlockError if waiting would deadlock."""
        self.detector.detect(start_ts, lock_ts)
        w = Waiter(start_ts, lock_ts, key)
        with self._mu:
            self._queues.setdefault(key, []).append(w)
        try:
            ok = w.event.wait(timeout if timeout is not None else self.default_timeout)
            return ok
        finally:
            self.detector.clean_up_wait_for(start_ts, lock_ts)
            with self._mu:
                q = self._queues.get(key)
                if q and w in q:
                    q.remove(w)

    def wait_info(self) -> list[dict]:
        """Current waits: who waits on whom for which key (the
        get_lock_wait_info RPC view, kv.rs:1061)."""
        with self._mu:
            return [
                {"key": w.key, "start_ts": w.start_ts, "lock_ts": w.lock_ts}
                for q in self._queues.values()
                for w in q
            ]

    def wake_up(self, key: bytes, released_ts: int) -> int:
        """Release waiters on ``key`` whose blocker was ``released_ts``."""
        with self._mu:
            q = self._queues.get(key, [])
            woken = [w for w in q if w.lock_ts == released_ts]
        for w in woken:
            w.event.set()
        self.detector.clean_up(released_ts)
        return len(woken)

    def wake_up_all(self, released_ts: int) -> int:
        """Release every waiter blocked on txn ``released_ts`` (any key)."""
        with self._mu:
            woken = [w for q in self._queues.values() for w in q if w.lock_ts == released_ts]
        for w in woken:
            w.event.set()
        self.detector.clean_up(released_ts)
        return len(woken)
