"""Pessimistic lock waiting + distributed deadlock detection.

Re-expression of ``src/server/lock_manager`` (waiter_manager.rs wait queues
with timeouts; deadlock.rs detector).  Waiters blocked on a lock register in
per-key queues; releases (commit/rollback) wake them in order.  The deadlock
detector maintains the waits-for graph (txn → txn) and rejects an edge that
would close a cycle, reporting the cycle's hash like the reference's
``deadlock_key_hash``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class DeadlockError(Exception):
    def __init__(self, waiting_txn: int, blocked_on_txn: int, cycle: list[int]):
        self.waiting_txn = waiting_txn
        self.blocked_on_txn = blocked_on_txn
        self.cycle = cycle
        super().__init__(f"deadlock: txn {waiting_txn} → {blocked_on_txn} closes cycle {cycle}")


class DeadlockDetector:
    """Waits-for graph with cycle check on edge insertion (deadlock.rs).

    In the reference this is a cluster-wide service hosted by region 1's
    leader; here it is a store-local authority with the same API, callable
    over the service layer for remote stores.
    """

    def __init__(self):
        self._mu = threading.Lock()
        # waits_for[a] = set of txns a waits on
        self.waits_for: dict[int, set[int]] = {}

    def detect(self, waiter_ts: int, lock_ts: int) -> None:
        """Register edge waiter→lock; raise DeadlockError if it closes a
        cycle.  The cycle lists each member ONCE, [lock_ts..waiter_ts] —
        the closing edge waiter→lock is implicit (wire encoders add it)."""
        with self._mu:
            cycle = self._path(lock_ts, waiter_ts)
            if cycle is not None:
                if cycle[-1] != waiter_ts:
                    cycle = cycle + [waiter_ts]
                raise DeadlockError(waiter_ts, lock_ts, cycle)
            self.waits_for.setdefault(waiter_ts, set()).add(lock_ts)

    def _path(self, frm: int, to: int) -> list[int] | None:
        seen = set()
        stack = [(frm, [frm])]
        while stack:
            node, path = stack.pop()
            if node == to:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.waits_for.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def clean_up_wait_for(self, waiter_ts: int, lock_ts: int) -> None:
        with self._mu:
            edges = self.waits_for.get(waiter_ts)
            if edges is not None:
                edges.discard(lock_ts)
                if not edges:
                    del self.waits_for[waiter_ts]

    def clean_up(self, txn_ts: int) -> None:
        with self._mu:
            self.waits_for.pop(txn_ts, None)


FIRST_REGION_ID = 1

_LEADER_UNSET = object()


class DetectorHandle:
    """Cluster-wide deadlock detection (deadlock.rs:343-391): the store
    holding REGION 1's leadership is the detector authority; every other
    store forwards its wait-for edges there over the wire (client.rs role).

    Role tracking is lazy: each call re-reads region 1's leader from the
    local raft store; when the observed leader changes, the local graph is
    reset (the reference clears on role-change callbacks — same effect, no
    observer plumbing).  When the leader is unknown or unreachable the edge
    degrades to the LOCAL graph: cross-store cycles then resolve by waiter
    timeout instead of detection — never a false positive."""

    def __init__(self, store, resolve, security=None):
        self.store = store          # raft store: leadership lookups
        self.resolve = resolve      # store_id -> (host, port) | None
        self.security = security
        self.local = DeadlockDetector()
        self._mu = threading.Lock()
        self._clients: dict[int, object] = {}
        self._last_leader: object = _LEADER_UNSET

    # -- leadership --------------------------------------------------------

    def _leader(self) -> int | None:
        leader = self.store.leader_store_of(FIRST_REGION_ID)
        with self._mu:
            if self._last_leader is not _LEADER_UNSET and leader != self._last_leader:
                # role CHANGED (not merely first observed — edges forwarded
                # to us before our first local detect must survive): the
                # graph we held is stale authority
                self.local = DeadlockDetector()
            self._last_leader = leader
        return leader

    def _call_leader(self, leader: int, payload: dict) -> dict | None:
        """One forwarded detector RPC; None = unreachable (degrade local)."""
        from .server import Client

        with self._mu:
            c = self._clients.get(leader)
        if c is None:
            addr = self.resolve(leader)
            if addr is None:
                return None
            try:
                c = Client(addr[0], addr[1], security=self.security)
            except OSError:
                return None
            with self._mu:
                self._clients[leader] = c
        try:
            return c.call("deadlock_detect", payload, timeout=2.0)
        except (ConnectionError, TimeoutError, OSError):
            with self._mu:
                self._clients.pop(leader, None)
            return None

    # -- DeadlockDetector surface (duck-typed for WaiterManager) -----------

    def detect(self, waiter_ts: int, lock_ts: int) -> None:
        leader = self._leader()
        if leader is None or leader == self.store.store_id:
            self.local.detect(waiter_ts, lock_ts)
            return
        resp = self._call_leader(
            leader, {"tp": "detect", "waiter_ts": waiter_ts, "lock_ts": lock_ts}
        )
        if resp is None or resp.get("not_leader") or resp.get("error"):
            # unreachable, stale leadership, or a leader that cannot serve
            # the detector RPC: degrade to the local graph (the edge must be
            # recorded SOMEWHERE or the cycle check silently disappears)
            self.local.detect(waiter_ts, lock_ts)
            return
        dl = resp.get("deadlock")
        if dl:
            raise DeadlockError(dl["waiting_txn"], dl["blocked_on_txn"], dl["cycle"])

    def _forward_cleanup(self, payload: dict) -> None:
        leader = self._leader()
        if leader is not None and leader != self.store.store_id:
            self._call_leader(leader, payload)

    def clean_up_wait_for(self, waiter_ts: int, lock_ts: int) -> None:
        self.local.clean_up_wait_for(waiter_ts, lock_ts)
        self._forward_cleanup(
            {"tp": "clean_up_wait_for", "waiter_ts": waiter_ts, "lock_ts": lock_ts}
        )

    def clean_up(self, txn_ts: int) -> None:
        self.local.clean_up(txn_ts)
        self._forward_cleanup({"tp": "clean_up", "txn_ts": txn_ts})

    def close(self) -> None:
        with self._mu:
            for c in self._clients.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._clients.clear()


@dataclass
class Waiter:
    start_ts: int
    lock_ts: int
    key: bytes
    event: threading.Event = field(default_factory=threading.Event)
    timed_out: bool = False


class WaiterManager:
    """Per-key wait queues with timeouts (waiter_manager.rs)."""

    def __init__(self, detector: DeadlockDetector | None = None, default_timeout: float = 3.0):
        self.detector = detector or DeadlockDetector()
        self.default_timeout = default_timeout
        self._mu = threading.Lock()
        self._queues: dict[bytes, list[Waiter]] = {}

    def wait_for(self, start_ts: int, lock_ts: int, key: bytes, timeout: float | None = None) -> bool:
        """Block until the lock on ``key`` is released.  Returns False on
        timeout.  Raises DeadlockError if waiting would deadlock."""
        self.detector.detect(start_ts, lock_ts)
        w = Waiter(start_ts, lock_ts, key)
        with self._mu:
            self._queues.setdefault(key, []).append(w)
        try:
            ok = w.event.wait(timeout if timeout is not None else self.default_timeout)
            return ok
        finally:
            self.detector.clean_up_wait_for(start_ts, lock_ts)
            with self._mu:
                q = self._queues.get(key)
                if q and w in q:
                    q.remove(w)

    def close(self) -> None:
        """Release detector resources (forwarding sockets + reader threads)."""
        close = getattr(self.detector, "close", None)
        if close is not None:
            close()

    def wait_info(self) -> list[dict]:
        """Current waits: who waits on whom for which key (the
        get_lock_wait_info RPC view, kv.rs:1061)."""
        with self._mu:
            return [
                {"key": w.key, "start_ts": w.start_ts, "lock_ts": w.lock_ts}
                for q in self._queues.values()
                for w in q
            ]

    def wake_up(self, key: bytes, released_ts: int) -> int:
        """Release waiters on ``key`` whose blocker was ``released_ts``."""
        with self._mu:
            q = self._queues.get(key, [])
            woken = [w for w in q if w.lock_ts == released_ts]
        for w in woken:
            w.event.set()
        self.detector.clean_up(released_ts)
        return len(woken)

    def wake_up_all(self, released_ts: int) -> int:
        """Release every waiter blocked on txn ``released_ts`` (any key)."""
        with self._mu:
            woken = [w for q in self._queues.values() for w in q if w.lock_ts == released_ts]
        for w in woken:
            w.event.set()
        self.detector.clean_up(released_ts)
        return len(woken)
