"""TLS for the framed-TCP wire.

Re-expression of ``components/security/src/lib.rs``: a SecurityConfig names a
CA plus the node's cert/key; when all three are set every server and client
socket is wrapped in **mutual** TLS (both sides verify against the CA, like
the reference's gRPC channel credentials), and the server can additionally
restrict accepted client certificates to an allow-list of Common Names
(``cert_allowed_cn``, lib.rs ``check_common_name``).

All-or-nothing validation matches the reference: setting only some of the
three paths is a config error rather than silent plaintext.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass, field


class SecurityError(Exception):
    pass


@dataclass
class SecurityConfig:
    ca_path: str = ""
    cert_path: str = ""
    key_path: str = ""
    cert_allowed_cn: set[str] = field(default_factory=set)

    def validate(self) -> None:
        paths = (self.ca_path, self.cert_path, self.key_path)
        if any(paths) and not all(paths):
            raise SecurityError("ca_path, cert_path and key_path must be set together")
        if self.cert_allowed_cn and not self.ca_path:
            raise SecurityError("cert_allowed_cn requires TLS to be configured")

    @property
    def enabled(self) -> bool:
        self.validate()
        return bool(self.ca_path)

    def server_context(self) -> ssl.SSLContext | None:
        if not self.enabled:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        ctx.load_verify_locations(self.ca_path)
        ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
        return ctx

    def client_context(self) -> ssl.SSLContext | None:
        if not self.enabled:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        ctx.load_verify_locations(self.ca_path)
        # peers are addressed by ip:port, identity comes from the shared CA
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def check_common_name(self, sock: ssl.SSLSocket) -> None:
        """Reject client certs whose CN is outside the allow-list."""
        if not self.cert_allowed_cn:
            return
        cert = sock.getpeercert()
        for rdn in (cert or {}).get("subject", ()):
            for k, v in rdn:
                if k == "commonName" and v in self.cert_allowed_cn:
                    return
        raise SecurityError("client certificate CN not in cert_allowed_cn")
