"""Self-describing wire codec for the RPC layer.

The reference speaks protobuf (kvproto) over gRPC; this framework's control
plane speaks a compact tagged encoding over TCP frames.  Supported values:
None, bool, int (signed 64), float, bytes, str, list, tuple, dict.  Safe to
decode untrusted bytes (no code execution, bounded recursion).
"""

from __future__ import annotations

from ..util import codec

_NONE, _TRUE, _FALSE, _INT, _FLOAT, _BYTES, _STR, _LIST, _DICT, _TUPLE = range(10)
_MAX_DEPTH = 32


def dumps(obj) -> bytes:
    out = bytearray()
    _enc(out, obj, 0)
    return bytes(out)


def _enc(out: bytearray, obj, depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("wire value too deep")
    if obj is None:
        out.append(_NONE)
    elif obj is True:
        out.append(_TRUE)
    elif obj is False:
        out.append(_FALSE)
    elif isinstance(obj, int):
        out.append(_INT)
        out += codec.encode_var_i64(obj)
    elif isinstance(obj, float):
        out.append(_FLOAT)
        out += codec.encode_f64(obj)
    elif isinstance(obj, bytes):
        out.append(_BYTES)
        out += codec.encode_var_u64(len(obj))
        out += obj
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(_STR)
        out += codec.encode_var_u64(len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        out.append(_LIST if isinstance(obj, list) else _TUPLE)
        out += codec.encode_var_u64(len(obj))
        for v in obj:
            _enc(out, v, depth + 1)
    elif isinstance(obj, dict):
        out.append(_DICT)
        out += codec.encode_var_u64(len(obj))
        for k, v in obj.items():
            _enc(out, k, depth + 1)
            _enc(out, v, depth + 1)
    else:
        raise TypeError(f"not wire-encodable: {type(obj)}")


def loads(b: bytes):
    v, off = _dec(b, 0, 0)
    if off != len(b):
        raise ValueError("trailing bytes")
    return v


def _dec(b: bytes, off: int, depth: int):
    if depth > _MAX_DEPTH:
        raise ValueError("wire value too deep")
    tag = b[off]
    off += 1
    if tag == _NONE:
        return None, off
    if tag == _TRUE:
        return True, off
    if tag == _FALSE:
        return False, off
    if tag == _INT:
        return codec.decode_var_i64(b, off)
    if tag == _FLOAT:
        return codec.decode_f64(b, off), off + 8
    if tag in (_BYTES, _STR):
        n, off = codec.decode_var_u64(b, off)
        raw = b[off : off + n]
        if len(raw) != n:
            raise ValueError("truncated")
        return (raw if tag == _BYTES else raw.decode()), off + n
    if tag in (_LIST, _TUPLE):
        n, off = codec.decode_var_u64(b, off)
        items = []
        for _ in range(n):
            v, off = _dec(b, off, depth + 1)
            items.append(v)
        return (items if tag == _LIST else tuple(items)), off
    if tag == _DICT:
        n, off = codec.decode_var_u64(b, off)
        d = {}
        for _ in range(n):
            k, off = _dec(b, off, depth + 1)
            v, off = _dec(b, off, depth + 1)
            d[k] = v
        return d, off
    raise ValueError(f"bad wire tag {tag}")
