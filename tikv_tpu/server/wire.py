"""Self-describing wire codec for the RPC layer.

The reference speaks protobuf (kvproto) over gRPC; this framework's control
plane speaks a compact tagged encoding over TCP frames.  Supported values:
None, bool, int (signed 64), float, bytes, str, list, tuple, dict.  Safe to
decode untrusted bytes (no code execution, bounded nesting).

The hot serving path is **zero-copy for large payloads** in both directions:

* :func:`dumps_parts` encodes a value into a list of buffers whose
  concatenation equals :func:`dumps` — but any ``bytes``-like payload of
  ``PASSTHROUGH_MIN`` bytes or more rides as its OWN buffer (a memoryview of
  the caller's object, never copied).  The server's frame writer hands the
  part list straight to ``socket.sendmsg`` (gather write), so a coprocessor
  response's chunk data crosses from the endpoint to the kernel with zero
  re-encoding copies.
* :func:`loads` accepts ``bytes``/``bytearray``/``memoryview`` input and
  walks it by offset (iterative containers, no per-element recursion for the
  encode side); with ``bytes_view=True`` payloads of ``PASSTHROUGH_MIN``
  bytes or more decode as **read-only** memoryviews into the frame instead
  of copies (opt-in: the default keeps the plain-``bytes`` contract).
  Read-only is a contract, not a convention: the views alias the shared
  frame buffer, so writing through one raises ``TypeError``
  (``memoryview.toreadonly``) — see docs/wire_path.md §zero-copy.
"""

from __future__ import annotations

from ..analysis import bufsan as _bufsan
from ..util import codec

_NONE, _TRUE, _FALSE, _INT, _FLOAT, _BYTES, _STR, _LIST, _DICT, _TUPLE = range(10)
_MAX_DEPTH = 32

#: bytes payloads at/above this size pass through as their own buffer
#: (dumps_parts) or decode as a memoryview (loads(bytes_view=True)).  Below
#: it, the copy is cheaper than the scatter/gather bookkeeping.
PASSTHROUGH_MIN = 2048

_BYTES_TYPES = (bytes, bytearray, memoryview)


def dumps(obj) -> bytes:
    out = bytearray()
    _encode(out, obj, None)
    return bytes(out)


def dumps_parts(obj) -> list:
    """Encode into a list of buffers; ``b"".join(map(bytes, parts))`` is
    byte-identical to ``dumps(obj)``.  Large bytes payloads become their own
    memoryview part — the caller's buffer, not a copy."""
    parts: list = []
    out = bytearray()
    _encode(out, obj, parts)
    if out:
        parts.append(bytes(out))
    return parts


def _encode(out: bytearray, root, parts: list | None) -> None:
    # explicit stack instead of per-element recursion: a 64k-row scan
    # response is a list of tens of thousands of pairs, and Python call
    # frames per element were the top line of the encode profile
    stack: list = [(root, 0)]
    while stack:
        obj, depth = stack.pop()
        if depth > _MAX_DEPTH:
            raise ValueError("wire value too deep")
        if obj is None:
            out.append(_NONE)
        elif obj is True:
            out.append(_TRUE)
        elif obj is False:
            out.append(_FALSE)
        elif isinstance(obj, int):
            out.append(_INT)
            out += codec.encode_var_i64(obj)
        elif isinstance(obj, float):
            out.append(_FLOAT)
            out += codec.encode_f64(obj)
        elif isinstance(obj, _BYTES_TYPES):
            n = len(obj)
            out.append(_BYTES)
            out += codec.encode_var_u64(n)
            if parts is not None and n >= PASSTHROUGH_MIN:
                # flush the accumulated header and pass the payload through
                # as the caller's own buffer — zero copies on this path.
                # The buffer is EXPOSED from here until the frame writer's
                # send completes: it must stay bit-stable (bufsan verifies
                # under TIKV_TPU_SANITIZE=1; write_frame_parts releases)
                parts.append(bytes(out))
                out.clear()
                part = obj if isinstance(obj, memoryview) else memoryview(obj)
                _bufsan.export("wire_part", part, site="wire.dumps_parts")
                parts.append(part)
            else:
                out += obj
        elif isinstance(obj, str):
            b = obj.encode()
            out.append(_STR)
            out += codec.encode_var_u64(len(b))
            out += b
        elif isinstance(obj, (list, tuple)):
            out.append(_LIST if isinstance(obj, list) else _TUPLE)
            out += codec.encode_var_u64(len(obj))
            d = depth + 1
            for v in reversed(obj):
                stack.append((v, d))
        elif isinstance(obj, dict):
            out.append(_DICT)
            out += codec.encode_var_u64(len(obj))
            d = depth + 1
            for k, v in reversed(list(obj.items())):
                stack.append((v, d))
                stack.append((k, d))
        else:
            raise TypeError(f"not wire-encodable: {type(obj)}")


def loads(b, bytes_view: bool = False):
    if isinstance(b, bytearray) or (bytes_view and isinstance(b, bytes)):
        b = memoryview(b)
    v, off = _dec(b, 0, 0, bytes_view)
    if off != len(b):
        raise ValueError("trailing bytes")
    return v


def _dec(b, off: int, depth: int, bytes_view: bool = False):
    if depth > _MAX_DEPTH:
        raise ValueError("wire value too deep")
    tag = b[off]
    off += 1
    if tag == _NONE:
        return None, off
    if tag == _TRUE:
        return True, off
    if tag == _FALSE:
        return False, off
    if tag == _INT:
        return codec.decode_var_i64(b, off)
    if tag == _FLOAT:
        return codec.decode_f64(b, off), off + 8
    if tag in (_BYTES, _STR):
        n, off = codec.decode_var_u64(b, off)
        raw = b[off : off + n]
        if len(raw) != n:
            raise ValueError("truncated")
        if tag == _STR:
            return (str(raw, "utf-8") if isinstance(raw, memoryview)
                    else raw.decode()), off + n
        if isinstance(raw, memoryview):
            # large payloads stay views into the frame (zero-copy decode);
            # small ones materialize — a dict full of tiny views would pin
            # the whole frame for the life of every key
            if bytes_view and n >= PASSTHROUGH_MIN:
                return raw.toreadonly(), off + n
            return bytes(raw), off + n
        return raw, off + n
    if tag in (_LIST, _TUPLE):
        n, off = codec.decode_var_u64(b, off)
        items = []
        for _ in range(n):
            v, off = _dec(b, off, depth + 1, bytes_view)
            items.append(v)
        return (items if tag == _LIST else tuple(items)), off
    if tag == _DICT:
        n, off = codec.decode_var_u64(b, off)
        d = {}
        for _ in range(n):
            k, off = _dec(b, off, depth + 1, bytes_view)
            v, off = _dec(b, off, depth + 1, bytes_view)
            d[k] = v
        return d, off
    raise ValueError(f"bad wire tag {tag}")
