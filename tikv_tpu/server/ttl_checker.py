"""Raw-KV TTL reclamation worker.

Re-expression of ``src/server/ttl/ttl_checker.rs:32`` +
``ttl_compaction_filter.rs:14``: the reference reclaims expired raw entries
during RocksDB compactions (the checker schedules compactions over ranges
whose TTL properties say they hold expired data).  Without compactions to
piggyback on, this build sweeps actively: a periodic scan over the raw
keyspace deletes entries whose expiry timestamp has passed.  Reads already
filter expired values lazily (storage.py) — the sweeper reclaims the space
and keeps scans from walking dead entries forever.

The reference's API-V1 rule applies verbatim: TTL-enabled raw KV must not
coexist with transactional data on the same store (the raw prefix byte can
collide with memcomparable-encoded txn keys).  The sweeper enforces it by
refusing to run while CF_WRITE holds any transactional records.
"""

from __future__ import annotations

import threading
import time

from ..storage.engine import CF_DEFAULT, CF_WRITE, WriteBatch
from ..storage.storage import RAW_PREFIX, _NO_TTL
from ..util import codec


class TtlChecker:
    def __init__(self, storage, interval: float = 5.0, batch: int = 1024):
        self.storage = storage
        self.interval = interval
        self.batch = batch
        self.reclaimed = 0
        self.errors = 0
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run_once(self, now: float | None = None, ctx: dict | None = None) -> int:
        """One sweep: delete every expired raw entry.  Returns the count."""
        now = int(now if now is not None else time.time())
        snap = self.storage.engine.snapshot(ctx)
        for _k, _v in snap.scan_cf(CF_WRITE, b"", None, limit=1):
            raise RuntimeError(
                "TTL checker requires a raw-mode store: transactional data "
                "present (API-V1 rule — RawKV TTL must not coexist with txn data)"
            )
        end = RAW_PREFIX[:-1] + bytes([RAW_PREFIX[-1] + 1])
        expired: list[bytes] = []
        for k, v in snap.scan_cf(CF_DEFAULT, RAW_PREFIX, end):
            if len(v) < 8:
                continue  # not a raw-codec value; never touch it
            expire = codec.decode_u64(v, len(v) - 8)
            if expire != _NO_TTL and expire <= now:
                expired.append(k)
        n = 0
        latches = self.storage._raw_latches
        for off in range(0, len(expired), self.batch):
            chunk = expired[off : off + self.batch]
            # serialize against concurrent raw writers and RE-CHECK expiry at
            # delete time — a key re-put after the snapshot must survive (the
            # reference's compaction filter checks expiry at filter time)
            cid = latches.gen_cid()
            slots = latches.acquire_blocking(cid, chunk)
            try:
                cur = self.storage.engine.snapshot(ctx)
                wb = WriteBatch()
                for k in chunk:
                    v = cur.get_cf(CF_DEFAULT, k)
                    if v is None or len(v) < 8:
                        continue
                    expire = codec.decode_u64(v, len(v) - 8)
                    if expire != _NO_TTL and expire <= now:
                        wb.delete_cf(CF_DEFAULT, k)
                        n += 1
                if wb.ops:
                    self.storage.engine.write(ctx, wb)
            finally:
                latches.release(cid, slots)
        self.reclaimed += n
        return n

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # already running — don't orphan the live loop
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception as exc:  # noqa: BLE001 — record, don't die
                self.errors += 1
                self.last_error = repr(exc)
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
