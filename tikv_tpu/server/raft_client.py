"""Peer raft transport over the framed-TCP wire.

Re-expression of ``src/server/raft_client.rs`` (:759 RaftClient, :844 send,
:934 flush, :479 per-store connection pool with backoff) and the snapshot
sender of ``src/server/snap.rs`` (:41): raft messages are buffered per target
store and flushed as ONE ``raft_batch`` frame (BatchRaftMessage), fire and
forget — raft tolerates a lossy channel, so a send failure drops the buffer
and backs off rather than blocking the raft loop.  Snapshot-bearing messages
bypass the batch stream and go as chunked ``snap_chunk`` frames.

``RemoteTransport`` adapts this to the raftstore ``Transport`` interface and
keeps the fault-injection ``Filter`` API of the in-memory transport, so the
scenario suite (partitions, drops) runs unchanged over real sockets.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Callable

from ..raft import net as raft_net
from ..raft.store import Filter, RaftMessage, Transport
from ..util.retry import RECONNECT_POLICY
from . import wire
from .server import write_frame

_MAX_BUFFERED = 4096


class _StoreConn:
    """One connection to a peer store (raft_client.rs per-store queue).
    The address re-resolves on every reconnect: a restarted store comes back
    on a different port and the resolver (PD in the reference, resolve.rs)
    is the source of truth.

    Locking: ``mu`` guards the pending-message buffer (held briefly by the
    raft thread); ``send_mu`` serializes ALL socket I/O including connect —
    the flusher thread and snapshot sender threads share this socket, and
    interleaved ``write_frame`` bytes would desync the receiver's framing."""

    def __init__(self, store_id: int, resolver, owner: "RaftClient"):
        self.store_id = store_id
        self.resolver = resolver
        self.owner = owner
        self.security = owner.security
        self.sock: socket.socket | None = None
        self.mu = threading.Lock()
        self.send_mu = threading.Lock()
        self.buf: list = []  # wire-encoded raft messages pending flush
        self.down_until = 0.0
        # consecutive reconnect failures: drives the shared exponential
        # policy (raft_client.rs:479's per-store backoff) — the first retry
        # probes quickly after a leader restart, a persistently dead store
        # decays toward the policy ceiling instead of being hammered twice a
        # second forever
        self.connect_failures = 0
        self.snap_inflight = False  # one snapshot transfer at a time per store

    def _mark_down_locked(self) -> None:
        self.connect_failures += 1
        self.down_until = time.monotonic() + RECONNECT_POLICY.backoff(
            self.connect_failures, self.owner.backoff_rng
        )

    def _connect_locked(self) -> bool:
        if self.sock is not None:
            return True
        if time.monotonic() < self.down_until:
            return False
        addr = self.resolver(self.store_id)
        if addr is None:
            self.owner.dropped_unresolved += 1
            self._mark_down_locked()
            return False
        try:
            sock = socket.create_connection((addr[0], addr[1]), timeout=2.0)
            if self.security is not None and self.security.enabled:
                sock = self.security.client_context().wrap_socket(sock)
            sock.settimeout(5.0)
            self.sock = sock
            self.connect_failures = 0
            return True
        except OSError:
            self.sock = None
            self._mark_down_locked()
            return False

    def send_oneway(self, method: str, req) -> bool:
        """Fire-and-forget frame (req_id 0 = no response expected)."""
        with self.send_mu:
            # lint: allow(lock-blocking-call) -- send_mu serializes exactly
            # this store's conn: connect-then-send must be one critical
            # section or two senders would race a half-open socket
            if not self._connect_locked():
                return False
            try:
                write_frame(self.sock, wire.dumps([0, method, req]))
                return True
            except OSError:
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.sock = None
                self._mark_down_locked()
                return False

    def close(self) -> None:
        with self.send_mu:
            if self.sock is not None:
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.sock = None


class RaftClient:
    """Buffers outgoing raft messages per store; a flusher thread ships them
    as batched frames.  ``resolver`` maps store_id -> (host, port) (the
    reference resolves through PD, resolve.rs:145)."""

    def __init__(self, resolver: Callable[[int], tuple[str, int] | None], security=None):
        import random

        self.resolver = resolver
        self.security = security
        self._conns: dict[int, _StoreConn] = {}
        self._mu = threading.Lock()
        # jitters the shared reconnect policy so N stores probing one
        # restarted peer don't reconnect in lockstep
        self.backoff_rng = random.Random()
        # transfer ids must be unique across every sending store feeding one
        # receiver's assembler: start at a random 62-bit offset per client
        self._xfer_ids = itertools.count(random.getrandbits(62) | (1 << 62))
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
        self._flusher.start()
        # lost-message accounting (metrics.rs raft_client counters)
        self.dropped_unresolved = 0
        self.dropped_send = 0
        self.dropped_full = 0

    def _conn_for(self, store_id: int) -> _StoreConn:
        with self._mu:
            conn = self._conns.get(store_id)
            if conn is None:
                conn = _StoreConn(store_id, self.resolver, self)
                self._conns[store_id] = conn
            return conn

    def evict(self, store_id: int) -> None:
        """Forget a (re-addressed or dead) store's connection."""
        with self._mu:
            conn = self._conns.pop(store_id, None)
        if conn is not None:
            conn.close()

    def send(self, store_id: int, rmsg: RaftMessage) -> None:
        conn = self._conn_for(store_id)
        if rmsg.msg.snapshot is not None and rmsg.msg.snapshot.data:
            # big payload: dedicated chunk stream on its own sender thread —
            # a multi-MB transfer on the raft thread would stall ticks and
            # heartbeats for every region on the store (the reference runs a
            # snap-sender task per transfer, snap.rs:41)
            with conn.mu:
                if conn.snap_inflight:
                    return  # raft re-queues the snapshot if the target stays behind
                conn.snap_inflight = True
            xid = next(self._xfer_ids)
            t = threading.Thread(
                target=self._send_snapshot, args=(conn, rmsg, xid), daemon=True
            )
            t.start()
            return
        with conn.mu:
            if len(conn.buf) >= _MAX_BUFFERED:
                self.dropped_full += 1
                return
            conn.buf.append(raft_net.rmsg_to_wire(rmsg))
        self._wake.set()

    def _send_snapshot(self, conn: _StoreConn, rmsg: RaftMessage, xid: int) -> None:
        try:
            for chunk in raft_net.split_snapshot(rmsg, xid):
                if not conn.send_oneway("raft_snapshot_chunk", chunk):
                    self.dropped_send += 1
                    return
        finally:
            with conn.mu:
                conn.snap_inflight = False

    def flush(self) -> None:
        """Ship every buffered message now (raft_client.rs:934)."""
        with self._mu:
            conns = list(self._conns.values())
        for conn in conns:
            with conn.mu:
                batch, conn.buf = conn.buf, []
            if not batch:
                continue
            if not conn.send_oneway("raft_batch", {"msgs": batch}):
                self.dropped_send += len(batch)

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.05)
            self._wake.clear()
            if self._stop.is_set():
                return
            # tiny linger so messages produced in one ready batch coalesce
            time.sleep(0.0005)
            self.flush()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._flusher.join(timeout=2)
        with self._mu:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            c.close()


class RemoteTransport(Transport):
    """raftstore Transport over RaftClient, with the in-memory transport's
    Filter hook retained for fault injection (transport_simulate.rs)."""

    def __init__(self, resolver: Callable[[int], tuple[str, int] | None], security=None):
        self.client = RaftClient(resolver, security=security)
        self.filters: list[Filter] = []

    def send(self, to_store: int, rmsg: RaftMessage) -> None:
        for f in self.filters:
            if not f.before(rmsg):
                return
        self.client.send(to_store, rmsg)

    def close(self) -> None:
        self.client.close()
