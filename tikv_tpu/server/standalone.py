"""Standalone store process: the ``run_tikv`` assembly entrypoint.

Re-expression of ``components/server/src/server.rs:105`` (run_tikv) +
``cmd/tikv-server/src/main.rs``: one OS process = one store.  Connects to PD
over TCP, opens (or recovers) the durable native engine, assembles
transport -> raftstore -> RaftKv -> Storage -> coprocessor -> KvService,
registers its address with PD, bootstraps region 1 if the cluster is virgin,
and serves until signalled.

Run:  python -m tikv_tpu.server.standalone \
          --store-id 1 --pd 127.0.0.1:2379 --dir /data/store1 --expect-stores 3
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from ..copr.endpoint import Endpoint
from ..pd.service import RemotePd
from ..raft.raftkv import RaftKv
from ..raft.region import Peer as RegionPeer, Region, RegionEpoch
from ..storage.storage import Storage
from .debug import Debugger
from .node import FIRST_REGION_ID, Node
from .raft_client import RemoteTransport
from .server import Server
from .service import KvService


def _default_mesh():
    """A (regions × groups) mesh over every visible device when more than one
    is present — the serving-path scale-out of BASELINE config #5.  Single
    device (or an unreachable backend) serves single-device; the Endpoint's
    CPU oracle remains the fallback either way."""
    try:
        import jax

        from ..parallel.mesh import make_mesh

        n = jax.device_count()
        if n <= 1:
            return None
        return make_mesh(groups=2 if n % 2 == 0 else 1)
    except Exception as exc:  # backend init failure must not block serving
        print(f"[standalone] device mesh unavailable, serving single-device: "
              f"{exc!r}", file=sys.stderr)
        return None


def open_engine(path: str | None, keys_mgr=None):
    if path is None:
        from ..storage.btree_engine import BTreeEngine

        return BTreeEngine()
    from ..native.engine import NativeEngine, native_available

    if not native_available():
        raise RuntimeError("native engine unavailable; cannot open a durable store")
    return NativeEngine(path=path, keys_mgr=keys_mgr)


def open_raft_log(data_dir: str | None, enable: bool = True, keys_mgr=None):
    """The raft_log_engine selection (components/server/src/server.rs:153-157):
    durable stores get the purpose-built segmented log by default; in-memory
    test stores keep the log in CF_RAFT."""
    if data_dir is None or not enable:
        return None
    import os

    from ..native.raftlog import NativeRaftLog, raftlog_available

    if not raftlog_available():
        return None
    return NativeRaftLog(os.path.join(data_dir, "raftlog"), keys_mgr=keys_mgr)


class StoreServer:
    """The assembled store (TiKVServer, components/server/src/server.rs:168)."""

    def __init__(
        self,
        store_id: int,
        pd: RemotePd,
        data_dir: str | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        enable_device: bool = False,
        security=None,
        raft_engine: bool = True,
        encryption_master_key: str | None = None,
        sched_continuous: bool = True,
        shard_cache: bool = True,
        group_commit: bool = True,
        write_through: bool = True,
        encode_columns: bool = True,
        integrity_scrub_interval: float = 10.0,
        shadow_sample: int | None = None,
        overload: bool = False,
        overload_rps: float = 0.0,
        overload_read_bps: float = 0.0,
        overload_max_priority: str = "high",
        cost_router: bool = True,
    ):
        self.pd = pd
        self.security = security
        self._peer_clients: dict[int, object] = {}
        from ..pd.feature_gate import FeatureGate

        # gate follows PD's cluster version (rolling-upgrade safety); synced
        # from the heartbeat loop below
        self.feature_gate = FeatureGate()
        # encryption at rest (manager/mod.rs:398): ONE DataKeyManager per
        # store seals the key dictionary under the master key; the raw data
        # keys feed both native engines' file IO and the importer's staged
        # files.  Every persistent byte the store writes is then encrypted.
        self.keys_mgr = None
        if encryption_master_key is not None:
            if data_dir is None:
                raise ValueError("encryption at rest requires a durable --dir")
            from ..storage.encryption import DataKeyManager, MasterKey

            os.makedirs(data_dir, exist_ok=True)
            self.keys_mgr = DataKeyManager.open(
                MasterKey.from_file(encryption_master_key),
                os.path.join(data_dir, "keys.dict"),
            )
        self.engine = open_engine(data_dir, keys_mgr=self.keys_mgr)
        if hasattr(self.engine, "start_auto_compaction"):
            # background version GC (rocksdb's compaction threads)
            self.engine.start_auto_compaction(interval_s=30.0)
        self.raft_log = open_raft_log(data_dir, enable=raft_engine,
                                      keys_mgr=self.keys_mgr)
        self.transport = RemoteTransport(self._resolve, security=security)
        self.node = Node(pd, self.transport, store_id=store_id, engine=self.engine,
                         raft_log=self.raft_log)
        if self.raft_log is not None and hasattr(self.engine, "set_sync"):
            # the raft log is the durable source of truth: apply writes run
            # buffered, flushed before log purge (reference sync-log split)
            self.engine.set_sync(False)
            self.node.store.kv_buffered = True
        self.store = self.node.store
        recovered = self.store.recover()
        from ..sidecar.resolved_ts import ResolvedTsEndpoint
        from .diagnostics import Diagnostics
        from .gc_worker import GcWorker
        from .lock_manager import DetectorHandle, WaiterManager

        self.resolved_ts = ResolvedTsEndpoint(
            pd, store_id=store_id, check_leader_send=self._check_leader_send,
            feature_gate=self.feature_gate,
        )
        self.resolved_ts.attach_store(self.store)
        self.raftkv = RaftKv(self.store, resolved_ts=self.resolved_ts)
        # the read-degradation ladder (docs/stale_reads.md): reads for
        # regions this store does not lead forward one hop to the leader,
        # degrade to follower stale serving when it is unreachable, or
        # refuse with leader + safe_ts hints
        from .read_plane import ReadPlane

        self.read_plane = ReadPlane(
            store=self.store, resolved_ts=self.resolved_ts,
            resolver=self._resolve, security=security,
        )
        # group commit (docs/write_path.md): queued compatible prewrites /
        # commits coalesce into one raft proposal; --no-group-commit reverts
        # to one proposal per command
        self.storage = Storage(engine=self.raftkv,
                               group_commit_max=16 if group_commit else 1)
        mesh = _default_mesh() if enable_device else None
        # cost-based path routing (docs/cost_router.md): --no-cost-router
        # forces the kill switch regardless of TIKV_TPU_COST_ROUTER
        from ..copr.costmodel import CostRouter, GeometryTuner

        self.copr = Endpoint(
            self.raftkv, enable_device=enable_device,
            mesh=mesh,
            feature_gate=self.feature_gate,
            shard_cache=shard_cache,
            write_through=write_through,
            encode_columns=encode_columns,
            shadow_sample=shadow_sample,
            cost_router=(CostRouter() if cost_router
                         else CostRouter(enabled=False)),
        )
        # overload control plane (docs/robustness.md "Overload"): always
        # CONSTRUCTED — so POST /config overload.enabled=true turns it on
        # at runtime — but disabled unless the operator opted in.  Quota
        # defaults come from the CLI/config; per-tenant overrides land via
        # OverloadControl.set_quota.
        from ..copr.overload import (
            OverloadConfig as _OvConfig, OverloadControl, TenantQuota,
        )

        self.overload = OverloadControl(
            _OvConfig(
                enabled=overload,
                default_quota=TenantQuota(
                    requests_per_s=overload_rps,
                    read_bytes_per_s=overload_read_bps,
                ),
                max_priority=overload_max_priority,
            ),
            region_cache=self.copr.region_cache,
        )
        self.copr.overload = self.overload
        # integrity plane (docs/integrity.md): the SDC scrubber verifies
        # warm images against the engine on a cadence; <=0 disables.
        # Shadow-read sampling is always on at its configured rate.
        if self.copr.scrubber is not None and integrity_scrub_interval > 0:
            self.copr.scrubber.start(integrity_scrub_interval)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            rc = self.copr.region_cache
            mode = ("sharded warm cache"
                    if rc is not None and getattr(rc, "sharded", False)
                    else "single-device warm cache")
            print(f"[standalone] serving mesh {dict(mesh.shape)} ({mode})",
                  file=sys.stderr)
        if sched_continuous:
            # continuous cross-region batching — ON BY DEFAULT since the
            # wire-path PR: unary coprocessor requests from concurrent
            # connections coalesce in the read scheduler's priority lanes
            # (service.coprocessor routes through it); same-plan-signature
            # requests across regions ride one vmapped device program and
            # identical requests share a slot (docs/wire_path.md)
            self.copr.scheduler.start()
        self.gc_worker = GcWorker(self.raftkv)
        # wait-for edges route to the cluster detector leader (region 1's
        # leader store); cross-store lock cycles break by error, not timeout
        self.lock_manager = WaiterManager(
            detector=DetectorHandle(self.store, self._resolve, security=security)
        )
        # store-wide memory attribution (tikv_util memory.rs MemoryTrace +
        # the server's memory-usage high-water): engine memtables, raft log
        # segments and CDC sink buffers report in; crossing the high-water
        # flushes the memtable — shedding instead of growing
        from ..sidecar.cdc import CdcService
        from ..util.memory import StoreMemoryTrace

        self.memory_trace = StoreMemoryTrace(f"store-{store_id}")
        if hasattr(self.engine, "mem_bytes"):
            self.memory_trace.child("engine_memtables", provider=self.engine.mem_bytes)
        if hasattr(self.engine, "wal_bytes"):
            self.memory_trace.child("engine_wal", provider=self.engine.wal_bytes)
        if self.raft_log is not None:
            self.memory_trace.child(
                "raft_log", provider=lambda: self.raft_log.stats()["active_size"]
            )
        self.cdc = CdcService(self.store, memory_trace=self.memory_trace)
        if hasattr(self.engine, "flush"):
            self.memory_trace.set_high_water(
                int(os.environ.get("TIKV_TPU_MEMORY_HIGH_WATER", str(4 << 30))),
                lambda total: self.engine.flush(),
            )
        # provider-backed trace nodes grow without add() calls: the heartbeat
        # re-evaluates the high-water condition, and reaps CDC subscriptions
        # whose client vanished (their buffers pin the shared quota)
        self.node.heartbeat_hooks.append(self.memory_trace.poll)

        def _sync_cluster_version():
            try:
                self.feature_gate.set_version(self.pd.get_cluster_version())
            except Exception:  # noqa: BLE001 — PD briefly unreachable
                pass

        _sync_cluster_version()
        self.node.heartbeat_hooks.append(_sync_cluster_version)
        # device-owner placement (docs/wire_path.md): advertise this store's
        # warm region images to PD each heartbeat and refresh the read
        # plane's owner route map from the response — the forwarding tier's
        # view of where every region's device image lives
        self.node.heartbeat_hooks.append(self._advertise_device_placement)
        self.node.heartbeat_hooks.append(lambda: self.cdc.reap_idle())
        from ..util.metrics import REGISTRY

        _mem_gauge = REGISTRY.gauge(
            "tikv_memory_usage_bytes", "Store memory-trace total")
        self.node.heartbeat_hooks.append(
            lambda: _mem_gauge.set(self.memory_trace.sum()))
        # engine internals for the operator dashboards (metrics/grafana/
        # tikv_tpu_engine.json): WAL size, memtable size, run counts per CF,
        # and the native perf counters (flushes, merges, block reads, bloom
        # skips) published as monotonic gauges each heartbeat
        self.node.heartbeat_hooks.append(self._publish_engine_metrics)
        # raw-KV TTL reclamation (ttl_checker.rs): a slow-cadence sweep of
        # expired raw entries through the replicated delete path, on its OWN
        # worker thread (the GcWorker AutoGc shape) — a large expired
        # backlog's raft round-trips must never stall the PD heartbeat loop
        from .ttl import TtlChecker

        self.ttl_checker = TtlChecker(self.storage)
        self._ttl_stop = threading.Event()

        def _ttl_loop(interval=float(os.environ.get("TIKV_TPU_TTL_SWEEP_SECS", "60"))):
            while not self._ttl_stop.wait(interval):
                for peer in list(self.store.peers.values()):
                    if self._ttl_stop.is_set():
                        return
                    if peer.node.is_leader():
                        try:
                            self.ttl_checker.sweep({"region_id": peer.region.id})
                        except Exception:  # noqa: BLE001 — next sweep retries
                            pass

        self._ttl_thread = threading.Thread(target=_ttl_loop, daemon=True,
                                            name="ttl-checker")
        # resolved-ts advance loop (endpoint.rs:247 advance-ts-interval):
        # periodic watermark advance with check_leader fan-out — what keeps
        # follower stale reads moving in the multi-process deployment
        self._rts_stop = threading.Event()

        def _rts_loop(interval=float(os.environ.get(
                "TIKV_TPU_RESOLVED_TS_INTERVAL", "1.0"))):
            while not self._rts_stop.wait(interval):
                try:
                    self.resolved_ts.advance_all()
                except Exception:  # noqa: BLE001 — next tick retries
                    pass

        self._rts_thread = threading.Thread(target=_rts_loop, daemon=True,
                                            name="resolved-ts-advance")
        # operator HTTP surface (status_server/mod.rs): /metrics, /status,
        # /debug/pprof/*, /debug/memory (the attribution tree above)
        from .status_server import StatusServer

        from ..util import trace
        from ..util.config import (
            ConfigController, CoprocessorConfig, OverloadSection, TikvConfig,
            TraceConfig,
        )

        self.config_controller = ConfigController(
            TikvConfig(
                coprocessor=CoprocessorConfig(enable_device=enable_device),
                # reflect the live tracer (env-seeded) so /config reads true
                trace=TraceConfig(sample_rate=trace.sample_rate(),
                                  slow_threshold_s=trace.slow_threshold()),
                overload=OverloadSection(
                    enabled=overload, requests_per_s=overload_rps,
                    read_bytes_per_s=overload_read_bps,
                    max_priority=overload_max_priority),
            )
        )
        # online overload knobs (docs/robustness.md "Overload"): POST
        # /config {"overload.enabled": true, "overload.requests_per_s": N}
        # — quota rates retune live, admission flips on/off at runtime
        self.config_controller.register(
            "overload", self.overload.reconfigure)
        # online coprocessor knobs: POST /config {"coprocessor.enable_device":
        # x, "coprocessor.block_rows": n, "coprocessor.max_wait_s": s} —
        # device toggle, block geometry (drops evaluators + warm images so
        # the next serve rebuilds at the new size), and the scheduler's
        # per-lane linger windows (docs/cost_router.md)

        def _copr_changed(changed: dict) -> None:
            if "enable_device" in changed:
                self.copr.set_enable_device(changed["enable_device"])
            if "block_rows" in changed:
                self.copr.set_block_rows(changed["block_rows"])
            waits = {k: v for k, v in changed.items()
                     if k in ("max_wait_s", "high_max_wait_s",
                              "low_max_wait_s")}
            if waits:
                self.copr.scheduler.reconfigure(waits)

        self.config_controller.register("coprocessor", _copr_changed)
        # geometry auto-tuner (docs/cost_router.md): hill-climbs block_rows
        # and the normal-lane linger from measured throughput — ONE change
        # in flight, applied through the SAME validated POST /config path
        # operators use, auto-reverted on a throughput floor regression
        tuner = GeometryTuner(enabled=self.copr.cost_router.enabled
                              and enable_device)
        tuner.register(
            "coprocessor.block_rows",
            lambda: self.config_controller.config.coprocessor.block_rows,
            lambda v: self.config_controller.update(
                {"coprocessor.block_rows": int(v)}),
            1 << 8, 1 << 20, integer=True)
        tuner.register(
            "coprocessor.max_wait_s",
            lambda: self.config_controller.config.coprocessor.max_wait_s,
            lambda v: self.config_controller.update(
                {"coprocessor.max_wait_s": float(v)}),
            0.0005, 0.05)
        self.copr.geometry_tuner = tuner
        self._tuner_stop = threading.Event()

        def _tuner_loop(interval=float(os.environ.get(
                "TIKV_TPU_TUNER_INTERVAL", "30"))):
            while not self._tuner_stop.wait(interval):
                try:
                    self.copr.geometry_tuner.tick()
                except Exception:  # noqa: BLE001 — next tick retries
                    pass

        self._tuner_thread = threading.Thread(target=_tuner_loop, daemon=True,
                                              name="geometry-tuner")
        # online tracing knobs (docs/tracing.md): POST /config
        # {"trace.sample_rate": r} — the ctl.py `trace set-sample-rate` path

        def _trace_changed(changed: dict) -> None:
            if "sample_rate" in changed:
                trace.set_sample_rate(changed["sample_rate"])
            if "slow_threshold_s" in changed:
                trace.set_slow_threshold(changed["slow_threshold_s"])

        self.config_controller.register("trace", _trace_changed)
        self.status_server = StatusServer(
            controller=self.config_controller,
            security=security, memory_trace=self.memory_trace,
            # stuck-follower debugging: per-region (resolved_ts,
            # required_apply_index) + the store safe_ts floor over HTTP
            read_progress=lambda: self.service.debug_read_progress({}),
            # derived-plane integrity: fingerprints, quarantine ledger,
            # scrubber + shadow-read state (docs/integrity.md)
            integrity=lambda: self.service.debug_integrity({}),
            # overload control plane: per-tenant buckets, controller scale,
            # HBM partition occupancy (docs/robustness.md "Overload")
            overload=lambda: self.service.debug_overload({}),
            # cost-router decisions + geometry tuner state
            # (docs/cost_router.md)
            cost_router=lambda: self.service.debug_cost_router({}),
        )
        self.service = KvService(
            self.storage,
            self.copr,
            debugger=Debugger(self.engine, raft_log=self.raft_log),
            pd=pd,
            raft_router=self.store,
            gc_worker=self.gc_worker,
            lock_manager=self.lock_manager,
            resolved_ts=self.resolved_ts,
            diagnostics=Diagnostics(),
            cdc=self.cdc,
            keys_rotator=self.rotate_data_keys if self.keys_mgr is not None else None,
            read_plane=self.read_plane,
            overload=self.overload,
        )
        self.server = Server(self.service, host=host, port=port, security=security)
        self.recovered_peers = recovered

    def _advertise_device_placement(self) -> None:
        rc = self.copr.region_cache
        regions: list[int] = []
        if rc is not None and self.copr.device_enabled():
            regions = rc.warm_region_ids()
        try:
            owners = self.pd.advertise_device_regions(
                self.store.store_id, regions)
        except Exception:  # noqa: BLE001 — PD briefly unreachable
            return
        if isinstance(owners, dict):
            self.read_plane.set_device_owners(owners)

    def _publish_engine_metrics(self) -> None:
        from ..util.metrics import REGISTRY

        eng = self.engine
        if hasattr(eng, "wal_bytes"):
            REGISTRY.gauge(
                "tikv_engine_wal_bytes", "Live WAL segment bytes"
            ).set(eng.wal_bytes())
        if hasattr(eng, "mem_bytes"):
            REGISTRY.gauge(
                "tikv_engine_memtable_bytes", "Memtable resident bytes"
            ).set(eng.mem_bytes())
        if hasattr(eng, "run_count"):
            g = REGISTRY.gauge("tikv_engine_run_count", "Sorted runs per CF")
            for cf in ("default", "write", "lock", "raft"):
                try:
                    g.set(eng.run_count(cf), cf=cf)
                except (ValueError, OSError):
                    pass
        if hasattr(eng, "perf_context"):
            g = REGISTRY.gauge(
                "tikv_engine_perf_events",
                "Native engine perf counters (monotonic; rate() in panels)",
            )
            for k, v in eng.perf_context().items():
                g.set(v, event=k)

    def _check_leader_send(self, store_id: int, payload: dict):
        """One check_leader RPC to a peer store (short timeout: a dead peer
        simply contributes no vote this round)."""
        addr = self._resolve(store_id)
        if addr is None:
            return None
        cl = self._peer_clients.get(store_id)
        try:
            if cl is None:
                from .server import Client

                cl = Client(addr[0], addr[1], security=self.security)
                self._peer_clients[store_id] = cl
            return cl.call("raft_check_leader", payload, timeout=2.0)
        except (OSError, ConnectionError, TimeoutError, RuntimeError):
            self._peer_clients.pop(store_id, None)
            try:
                if cl is not None:
                    cl.close()
            except OSError:
                pass
            return None

    def rotate_data_keys(self) -> dict:
        """Mint ONE new data key and refresh every native engine's registry:
        files written from now on use it, existing files keep their sidecar
        key (debug_rotate_data_key RPC surface)."""
        new_id = self.keys_mgr.rotate()
        self.engine.refresh_encryption()
        if self.raft_log is not None:
            self.raft_log.refresh_encryption()
        return {"key_id": new_id}

    def _resolve(self, store_id: int):
        try:
            return self.pd.get_store_addr(store_id)
        except Exception:  # noqa: BLE001 — PD briefly unreachable
            return None

    def start(self) -> None:
        self.server.start()
        self.status_server.start()
        self._ttl_thread.start()
        self._rts_thread.start()
        self._tuner_thread.start()
        self.pd.put_store(self.store.store_id, addr=self.server.addr)
        self.node.start()

    def bootstrap_or_join(self, expect_stores: int, timeout: float = 30.0) -> None:
        """Cluster formation (node.rs:153 try_bootstrap): wait until
        ``expect_stores`` stores registered; the lowest id bootstraps region
        1 spanning all of them; everyone creates local peers placed here.
        A recovered store skips formation — its peers came off disk."""
        if self.recovered_peers:
            return
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            region = self.pd.get_region_by_id(FIRST_REGION_ID)
            if region is not None:
                me = region.peer_on_store(self.store.store_id)
                if me is not None and region.id not in self.store.peers:
                    self.store.create_peer(region)
                    if self.store.store_id == min(p.store_id for p in region.peers):
                        self.store.peers[region.id].node.campaign()
                return
            stores = sorted(self.pd.alive_stores())
            if len(stores) >= expect_stores:
                if self.store.store_id == stores[0]:
                    peers = [RegionPeer(self.pd.alloc_id(), sid) for sid in stores[:expect_stores]]
                    region = Region(FIRST_REGION_ID, b"", b"", RegionEpoch(), peers)
                    self.pd.bootstrap_region(region)
                    continue  # next loop iteration takes the join path
            time.sleep(0.1)
        raise TimeoutError("cluster never formed")

    def stop(self) -> None:
        if self.copr.scrubber is not None:
            self.copr.scrubber.stop()
        self.copr.scheduler.stop()
        self._ttl_stop.set()
        self._rts_stop.set()
        self._tuner_stop.set()
        # the advance thread inserts into _peer_clients: join it BEFORE
        # closing/iterating the clients
        if self._rts_thread.is_alive():
            self._rts_thread.join(timeout=10.0)
        for cl in list(self._peer_clients.values()):
            try:
                cl.close()
            except OSError:
                pass
        self.read_plane.close()
        self.node.stop()
        self.server.stop()
        self.status_server.stop()
        self.transport.close()
        self.lock_manager.close()
        close = getattr(self.engine, "close", None)
        if close is not None:
            close()
        if self.raft_log is not None:
            self.raft_log.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="tikv_tpu store server")
    ap.add_argument("--store-id", type=int, required=True)
    ap.add_argument("--pd", required=True, help="host:port of the PD service")
    ap.add_argument("--dir", default=None, help="durable engine directory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--expect-stores", type=int, default=1)
    ap.add_argument("--enable-device", action="store_true")
    ap.add_argument("--sched-continuous", action="store_true",
                    help="deprecated no-op: continuous coalescing is the "
                         "default (see --no-sched-continuous)")
    ap.add_argument("--no-sched-continuous", action="store_true",
                    help="serve unary coprocessor requests per-request "
                         "instead of coalescing them across connections in "
                         "the read scheduler's priority lanes")
    ap.add_argument("--no-shard-cache", action="store_true",
                    help="keep the region column cache single-device even "
                         "with a multi-chip mesh (sharded warm serving off)")
    ap.add_argument("--no-group-commit", action="store_true",
                    help="one raft proposal per txn command instead of "
                         "coalescing queued prewrites/commits (write_path.md)")
    ap.add_argument("--no-column-encoding", action="store_true",
                    help="keep region images device-resident DECODED "
                         "(docs/compressed_columns.md kill switch; budgets "
                         "then account decoded bytes)")
    ap.add_argument("--no-write-through", action="store_true",
                    help="disable raft-apply delta emission into the region "
                         "column cache (warm reads repair via scan_delta)")
    ap.add_argument("--integrity-scrub-interval", type=float, default=10.0,
                    help="seconds between SDC scrubber rounds over warm "
                         "region images (docs/integrity.md); <=0 disables")
    ap.add_argument("--overload", action="store_true",
                    help="enable the overload control plane: per-tenant "
                         "quota admission, priority clamping, adaptive "
                         "shedding (docs/robustness.md)")
    ap.add_argument("--overload-rps", type=float, default=0.0,
                    help="default-tenant requests/s quota (0 = unlimited)")
    ap.add_argument("--overload-read-bps", type=float, default=0.0,
                    help="default-tenant read-bytes/s quota (0 = unlimited)")
    ap.add_argument("--overload-max-priority", default="high",
                    choices=["high", "normal", "low"],
                    help="lane ceiling for client-declared priorities")
    ap.add_argument("--shadow-sample", type=int, default=None,
                    help="shadow-read 1-in-N sampling of warm device serves "
                         "(default 256 or TIKV_TPU_SHADOW_SAMPLE; 0 "
                         "disables, 1 verifies every warm serve)")
    ap.add_argument("--trace-sample", type=float, default=None,
                    help="distributed-tracing head sample rate in [0,1] "
                         "(default 0.01 or TIKV_TPU_TRACE_SAMPLE; 0 turns "
                         "the tracing plane off; docs/tracing.md)")
    ap.add_argument("--no-cost-router", action="store_true",
                    help="kill switch for cost-based path routing + the "
                         "geometry auto-tuner: serve with the static rule "
                         "ladder exactly (docs/cost_router.md; equivalent "
                         "to TIKV_TPU_COST_ROUTER=0)")
    ap.add_argument("--no-raft-engine", action="store_true",
                    help="keep the raft log in CF_RAFT instead of the segmented log engine")
    ap.add_argument("--ca-path", default="")
    ap.add_argument("--cert-path", default="")
    ap.add_argument("--key-path", default="")
    ap.add_argument("--redact-info-log", default="off", choices=["off", "on", "marker"])
    ap.add_argument("--encryption-master-key", default=None,
                    help="path to a 32-byte master key file: encrypt every "
                         "engine/raft-log file at rest (data keys sealed "
                         "under it in <dir>/keys.dict)")
    args = ap.parse_args(argv)

    from ..util import logger as slog
    from .security import SecurityConfig

    if args.trace_sample is not None:
        from ..util import trace as _trace

        _trace.set_sample_rate(args.trace_sample)
    slog.set_redact_info_log(args.redact_info_log)
    security = SecurityConfig(
        ca_path=args.ca_path, cert_path=args.cert_path, key_path=args.key_path
    )
    security.validate()
    if not security.enabled:
        security = None

    host, port = args.pd.rsplit(":", 1)
    pd = RemotePd(host, int(port), security=security)
    srv = StoreServer(
        args.store_id, pd, data_dir=args.dir,
        host=args.host, port=args.port, enable_device=args.enable_device,
        security=security, raft_engine=not args.no_raft_engine,
        encryption_master_key=args.encryption_master_key,
        sched_continuous=not args.no_sched_continuous,
        shard_cache=not args.no_shard_cache,
        group_commit=not args.no_group_commit,
        write_through=not args.no_write_through,
        encode_columns=not args.no_column_encoding,
        integrity_scrub_interval=args.integrity_scrub_interval,
        shadow_sample=args.shadow_sample,
        overload=args.overload,
        overload_rps=args.overload_rps,
        overload_read_bps=args.overload_read_bps,
        overload_max_priority=args.overload_max_priority,
        cost_router=not args.no_cost_router,
    )
    srv.start()
    srv.bootstrap_or_join(args.expect_stores)
    print(f"READY store={args.store_id} addr={srv.server.addr[0]}:{srv.server.addr[1]}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
