"""Diagnostics service: log search + server info.

Re-expression of ``src/server/service/diagnostics/`` (registered at
components/server/src/server.rs:907): `search_log` greps the store's log
file(s) with level/pattern/time filters and `server_info` reports hardware,
system and process facts — what tidb's `SELECT * FROM information_schema
.cluster_log / .cluster_hardware` pulls from each store.
"""

from __future__ import annotations

import os
import platform
import re
import time

LEVELS = ("DEBUG", "INFO", "WARN", "ERROR", "CRITICAL")


class Diagnostics:
    def __init__(self, log_path: str | None = None):
        self.log_path = log_path
        self.start_time = time.time()

    # -- log search (diagnostics/log.rs) ------------------------------------

    def search_log(
        self,
        patterns: list[str] | None = None,
        levels: list[str] | None = None,
        start_time: float | None = None,
        end_time: float | None = None,
        limit: int = 1024,
    ) -> list[dict]:
        """Scan the log file; a line matches when every regex pattern hits,
        its level is in ``levels`` (if given), and its leading ISO timestamp
        falls inside [start_time, end_time] (lines without a parseable
        timestamp pass the time filter)."""
        if self.log_path is None or not os.path.exists(self.log_path):
            return []
        regexes = [re.compile(p) for p in (patterns or [])]
        lvl = {l.upper() for l in levels} if levels else None
        out: list[dict] = []
        with open(self.log_path, "r", errors="replace") as f:
            for line in f:
                line = line.rstrip("\n")
                if regexes and not all(r.search(line) for r in regexes):
                    continue
                level = next((l for l in LEVELS if l in line[:64]), "INFO")
                if lvl is not None and level not in lvl:
                    continue
                ts = _parse_line_time(line)
                if ts is not None:
                    if start_time is not None and ts < start_time:
                        continue
                    if end_time is not None and ts > end_time:
                        continue
                out.append({"time": ts, "level": level, "message": line})
                if len(out) >= limit:
                    break
        return out

    # -- server info (diagnostics/sys.rs) -----------------------------------

    def server_info(self) -> dict:
        info: dict = {
            "hostname": platform.node(),
            "os": platform.system(),
            "kernel": platform.release(),
            "arch": platform.machine(),
            "python": platform.python_version(),
            "pid": os.getpid(),
            "uptime_secs": round(time.time() - self.start_time, 1),
            "cpu_count": os.cpu_count(),
        }
        try:
            info["load_avg"] = list(os.getloadavg())
        except OSError:
            pass
        mem = _meminfo()
        if mem:
            info["memory"] = mem
        try:
            st = os.statvfs("/")
            info["disk"] = {
                "total_bytes": st.f_blocks * st.f_frsize,
                "available_bytes": st.f_bavail * st.f_frsize,
            }
        except OSError:
            pass
        return info


def _parse_line_time(line: str) -> float | None:
    m = re.match(r"^[\[]?(\d{4}-\d{2}-\d{2}[ T]\d{2}:\d{2}:\d{2})", line)
    if m is None:
        return None
    try:
        return time.mktime(time.strptime(m.group(1).replace("T", " "), "%Y-%m-%d %H:%M:%S"))
    except ValueError:
        return None


def _meminfo() -> dict:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                if k in ("MemTotal", "MemAvailable"):
                    out[k] = int(rest.strip().split()[0]) * 1024
    except OSError:
        return {}
    return out
