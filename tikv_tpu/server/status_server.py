"""HTTP status server: /metrics, /status, /config (GET + POST reconfig).

Re-expression of ``src/server/status_server/mod.rs:720-745``: the operator
surface — Prometheus exposition, liveness, config inspection, and online
reconfiguration via POST /config dispatched through the ConfigController.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..util.metrics import REGISTRY
from ..util.config import ConfigController


class StatusServer:
    def __init__(self, controller: ConfigController | None = None, host="127.0.0.1", port=0, registry=None):
        self.controller = controller
        self.registry = registry or REGISTRY
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype="text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, outer.registry.render().encode())
                elif self.path == "/status":
                    self._send(200, b"ok")
                elif self.path == "/config":
                    cfg = outer.controller.config.to_dict() if outer.controller else {}
                    self._send(200, json.dumps(cfg).encode(), "application/json")
                else:
                    self._send(404, b"not found")

            def do_POST(self):
                if self.path != "/config" or outer.controller is None:
                    self._send(404, b"not found")
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    changes = json.loads(self.rfile.read(n) or b"{}")
                    diff = outer.controller.update(changes)
                    self._send(200, json.dumps(diff).encode(), "application/json")
                except (ValueError, TypeError) as e:
                    self._send(400, str(e).encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._httpd.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
