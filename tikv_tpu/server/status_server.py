"""HTTP status server: /metrics, /status, /config, /debug/pprof/*.

Re-expression of ``src/server/status_server/mod.rs:720-745``: the operator
surface — Prometheus exposition, liveness, config inspection, online
reconfiguration via POST /config dispatched through the ConfigController,
and the profiling endpoints (profile.rs): GET /debug/pprof/profile?seconds=N
(CPU) and GET /debug/pprof/heap (allocation sites).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..util.metrics import REGISTRY
from ..util.config import ConfigController
from .profiler import Profiler


class StatusServer:
    def __init__(self, controller: ConfigController | None = None, host="127.0.0.1", port=0, registry=None,
                 security=None, memory_trace=None, read_progress=None,
                 integrity=None, overload=None, cost_router=None):
        self.controller = controller
        self.security = security
        self.registry = registry or REGISTRY
        self.profiler = Profiler()
        self.memory_trace = memory_trace
        # callable returning {"safe_ts", "regions": {rid: {resolved_ts,
        # required_apply_index}}} — the stuck-follower stale-read surface
        self.read_progress = read_progress
        # callable returning the integrity-plane view (docs/integrity.md):
        # image fingerprints, quarantine ledger, scrubber + shadow state
        self.integrity = integrity
        # callable returning the overload-control view (docs/robustness.md
        # "Overload"): tenant buckets, controller scale, HBM partitions
        self.overload = overload
        # callable returning the cost-router + geometry-tuner view
        # (docs/cost_router.md): decision counts/ring, tuner history
        self.cost_router = cost_router
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def setup(self):
                # TLS: the listener wraps with do_handshake_on_connect=False
                # so accept() never blocks on a silent client; the handshake
                # (+ CN allow-list, same as Server._handshake_and_serve) runs
                # here, on this connection's own thread, under a timeout.
                if outer.security is not None and outer.security.enabled:
                    self.request.settimeout(10.0)
                    self.request.do_handshake()
                    outer.security.check_common_name(self.request)
                super().setup()

            def _send(self, code: int, body: bytes, ctype="text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_traces(self, url):
                from ..util import trace

                q = parse_qs(url.query)
                tid = q.get("trace_id", [None])[0]
                as_json = q.get("format", [""])[0] == "json"
                try:
                    limit = int(q.get("limit", ["20"])[0])
                except ValueError:
                    self._send(400, b"limit must be an integer")
                    return
                if tid:
                    t = trace.TRACER.get(tid)
                    if t is None:
                        self._send(404, f"trace {tid} not found".encode())
                        return
                    if as_json:
                        self._send(200, json.dumps(t).encode(),
                                   "application/json")
                    else:
                        self._send(200, trace.timeline(t).encode())
                    return
                snap = trace.snapshot(limit=limit)
                if as_json:
                    self._send(200, json.dumps(snap).encode(),
                               "application/json")
                    return
                lines = [
                    f"sample_rate={snap['sample_rate']} "
                    f"slow_threshold_s={snap['slow_threshold_s']} "
                    f"live={snap['live']}",
                ]
                for ring in ("slow", "recent"):
                    lines.append(f"-- {ring} ({len(snap[ring])}) --")
                    for t in reversed(snap[ring]):
                        lines.append(trace.timeline(t))
                self._send(200, "\n".join(lines).encode())

            def _serve_observatory(self, url):
                from ..copr import observatory as obs

                q = parse_qs(url.query)
                sig = q.get("sig", [None])[0]
                as_json = q.get("format", [""])[0] == "json"
                try:
                    limit = int(q.get("limit", ["20"])[0])
                except ValueError:
                    self._send(400, b"limit must be an integer")
                    return
                snap = obs.OBSERVATORY.snapshot(sig=sig)
                if as_json:
                    self._send(200, json.dumps(snap).encode(),
                               "application/json")
                    return
                if sig:
                    entry = snap["sigs"].get(sig)
                    if entry is None:
                        self._send(404, f"sig {sig} not profiled".encode())
                        return
                    body = obs.format_sig(sig, entry)
                else:
                    comp = snap["compiles"]
                    body = "\n".join([
                        f"observatory: sigs={snap['live_sigs']} "
                        f"evicted={snap['evicted_sigs']} "
                        f"window={snap['window_s']}s x{snap['n_windows']} "
                        f"compiles={len(comp['events'])}",
                        obs.format_top(obs.OBSERVATORY.top(limit)),
                    ])
                    declines = self._decline_lines()
                    if declines:
                        body += "\n-- device-plan declines --\n" + "\n".join(declines)
                self._send(200, body.encode())

            @staticmethod
            def _decline_lines() -> list[str]:
                # per-cause device-plan decline counts, next to the path
                # profiles: why the encoded path keeps falling back matters
                # when reading the cost router's cold/explore decisions
                c = outer.registry.counter(
                    "tikv_coprocessor_encoded_decline_total",
                    "Encoded-path declines (decode-ship / CPU), by path and cause")
                with c._mu:
                    items = sorted(c._values.items())
                return [
                    "  " + " ".join(f"{k}={v}" for k, v in key) + f": {int(n)}"
                    for key, n in items
                ]

            def do_GET(self):
                url = urlparse(self.path)
                if url.path == "/metrics":
                    self._send(200, outer.registry.render().encode())
                elif url.path == "/status":
                    self._send(200, b"ok")
                elif url.path == "/config":
                    cfg = outer.controller.config.to_dict() if outer.controller else {}
                    self._send(200, json.dumps(cfg).encode(), "application/json")
                elif url.path == "/debug/pprof/profile":
                    q = parse_qs(url.query)
                    try:
                        seconds = float(q.get("seconds", ["1"])[0])
                    except ValueError:
                        self._send(400, b"seconds must be a number")
                        return
                    raw = q.get("raw", ["0"])[0] == "1"
                    try:
                        body = outer.profiler.cpu_profile(min(seconds, 60.0), raw=raw)
                    except RuntimeError as e:
                        self._send(429, str(e).encode())
                        return
                    ctype = "application/octet-stream" if raw else "text/plain"
                    self._send(200, body, ctype)
                elif url.path == "/debug/traces":
                    # recent + slow request traces (docs/tracing.md): the
                    # indented-timeline text view by default, the raw trace
                    # dicts with ?format=json, one trace with ?trace_id=
                    self._serve_traces(url)
                elif url.path == "/debug/read_progress":
                    # per-region RegionReadProgress + store safe_ts: why a
                    # follower refuses stale reads (docs/stale_reads.md)
                    if outer.read_progress is None:
                        self._send(404, b"no resolved-ts endpoint wired")
                        return
                    self._send(200, json.dumps(outer.read_progress()).encode(),
                               "application/json")
                elif url.path == "/debug/observatory":
                    # performance observatory (docs/observatory.md): per-sig
                    # path cost profiles + the compile ledger + HBM
                    # watermarks.  ?sig= narrows, ?format=json for the raw
                    # snapshot, default text = profiler-style top
                    self._serve_observatory(url)
                elif url.path == "/debug/integrity":
                    # derived-plane integrity: fingerprints, quarantine
                    # ledger, scrubber + shadow-read state (docs/integrity.md)
                    if outer.integrity is None:
                        self._send(404, b"no integrity surface wired")
                        return
                    self._send(200, json.dumps(outer.integrity()).encode(),
                               "application/json")
                elif url.path == "/debug/overload":
                    # overload control plane: per-tenant bucket levels +
                    # effective rates, shed/defer counts, adaptive scale,
                    # HBM partition occupancy (docs/robustness.md)
                    if outer.overload is None:
                        self._send(404, b"no overload control wired")
                        return
                    self._send(200, json.dumps(outer.overload()).encode(),
                               "application/json")
                elif url.path == "/debug/cost_router":
                    # cost-based path router + geometry tuner: per-sig
                    # decision counts, recent decisions, tuner knob history
                    # (docs/cost_router.md)
                    if outer.cost_router is None:
                        self._send(404, b"no cost router wired")
                        return
                    self._send(200, json.dumps(outer.cost_router()).encode(),
                               "application/json")
                elif url.path == "/debug/memory":
                    # the store's memory-attribution tree (MemoryTrace)
                    if outer.memory_trace is None:
                        self._send(404, b"no memory trace wired")
                        return
                    self._send(200, json.dumps(outer.memory_trace.snapshot()).encode(),
                               "application/json")
                elif url.path == "/debug/pprof/heap":
                    q = parse_qs(url.query)
                    try:
                        top = int(q.get("top", ["50"])[0])
                    except ValueError:
                        self._send(400, b"top must be an integer")
                        return
                    self._send(200, outer.profiler.heap_profile(top=top))
                else:
                    self._send(404, b"not found")

            def do_POST(self):
                if self.path != "/config" or outer.controller is None:
                    self._send(404, b"not found")
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    changes = json.loads(self.rfile.read(n) or b"{}")
                    diff = outer.controller.update(changes)
                    self._send(200, json.dumps(diff).encode(), "application/json")
                except (ValueError, TypeError) as e:
                    self._send(400, str(e).encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        # status_server/mod.rs wires the same TLS config into the status
        # listener; when [security] is set we serve mutual-TLS HTTPS here too.
        # Handshake is deferred to the per-connection thread (Handler.setup)
        # so one silent client can't wedge the accept loop.
        if security is not None and security.enabled:
            self._httpd.socket = security.server_context().wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.addr = self._httpd.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # shutdown() BLOCKS until serve_forever acknowledges — which never
        # happens when the server was constructed but not started
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
