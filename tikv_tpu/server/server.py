"""TCP server: the node's RPC front door.

Re-expression of ``src/server/server.rs`` + the ``batch_commands`` stream
(service/kv.rs:891): one socket per client, length-prefixed frames, each frame
``[req_id, method, request]`` (wire codec) answered out of order —
multiplexed like batch_commands.  A thread-pool executes handlers so slow
commands don't block the socket reader.
"""

from __future__ import annotations

import inspect
import os
import queue
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..analysis import bufsan as _bufsan
from ..util import error_code, trace
from ..util.metrics import REGISTRY
from ..util.worker import TaskPriority, UnifiedReadPool
from . import wire
from .service import KvService

# the reference's grpc request metrics (tikv_grpc_msg_* in metrics.rs):
# per-method counts + latency over the framed-TCP transport
GRPC_MSG_TOTAL = REGISTRY.counter(
    "tikv_grpc_msg_total", "RPCs served, by method")
GRPC_MSG_DURATION = REGISTRY.histogram(
    "tikv_grpc_msg_duration_seconds", "RPC handling latency, by method")
GRPC_MSG_FAIL = REGISTRY.counter(
    "tikv_grpc_msg_fail_total", "RPCs that returned an error, by method")
# per-stage wire-path breakdown (docs/wire_path.md): where a served frame's
# time goes — decode (frame bytes -> request value), route (read/handler
# pool queue wait), execute (service dispatch), encode (response value ->
# socket).  THE profiling surface for the decode->endpoint->encode gap;
# summarized by bench_cluster.py and the debug_wire_stages RPC.
WIRE_STAGE = REGISTRY.histogram(
    "tikv_wire_stage_seconds",
    "Wire-path time per served frame, by stage",
    buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1, 5),
)

error_code.register_builtin()

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 << 20

# read-path RPCs go through the unified read pool (src/read_pool.rs routes
# point gets / scans / coprocessor there); writes keep the plain executor so
# a saturated analytical workload can't starve the write path's threads
# max unacked streamed frames in flight per stream (gRPC window analog);
# both sides hold at most this many frames regardless of consumer speed
STREAM_WINDOW = 8
# a stream whose consumer sends no ack (and no cancel) for this long is
# dropped so it cannot pin a read-pool worker indefinitely
STREAM_IDLE_TIMEOUT = 300.0

_READ_METHODS = (
    "kv_get", "kv_batch_get", "kv_scan", "kv_scan_lock",
    "raw_get", "raw_batch_get", "raw_scan", "raw_batch_scan", "raw_get_key_ttl",
    "coprocessor", "coprocessor_stream", "coprocessor_batch", "raw_coprocessor",
    "mvcc_get_by_key", "mvcc_get_by_start_ts",
)


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> bytes | None:
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > _MAX_FRAME:
        raise ValueError("frame too large")
    return _read_exact(sock, n)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


try:
    #: the kernel rejects a sendmsg with more iovecs than this (EMSGSIZE) —
    #: a many-payload response (batch coprocessor) must gather in slices
    _IOV_MAX = os.sysconf("SC_IOV_MAX")
except (AttributeError, OSError, ValueError):
    _IOV_MAX = 1024


def write_frame_parts(sock: socket.socket, parts: list) -> None:
    """One frame from a ``wire.dumps_parts`` buffer list: gather-write via
    ``sendmsg`` so a large response payload (coprocessor chunk data) goes
    header + passthrough buffers straight to the kernel — no single-buffer
    concatenation copy.  TLS sockets (no sendmsg) fall back to a join.

    This is the RELEASE boundary of the zero-copy exposure window: once the
    send completes (or the socket dies), the passthrough buffers are no
    longer aliased by the kernel, and bufsan verifies each one's sample
    against its ``dumps_parts`` registration."""
    try:
        bufs = [memoryview(_LEN.pack(sum(len(p) for p in parts)))]
        bufs += [p if isinstance(p, memoryview) else memoryview(p) for p in parts]
        sendmsg = getattr(sock, "sendmsg", None)
        if sendmsg is None:
            sock.sendall(b"".join(bufs))
            return
        try:
            sent = sendmsg(bufs[:_IOV_MAX])
        except (NotImplementedError, OSError) as e:
            if isinstance(e, OSError):
                raise
            sock.sendall(b"".join(bufs))  # ssl.SSLSocket raises NotImplementedError
            return
        # a partial gather write is legal: advance through the buffer list
        while True:
            while bufs and sent >= len(bufs[0]):
                sent -= len(bufs[0])
                bufs.pop(0)
            if not bufs:
                return
            if sent:
                bufs[0] = bufs[0][sent:]
            sent = sendmsg(bufs[:_IOV_MAX])
    finally:
        _bufsan.release_parts(parts, site="server.write_frame_parts")


class Server:
    def __init__(
        self,
        service: KvService,
        host: str = "127.0.0.1",
        port: int = 0,
        workers: int = 8,
        security=None,
        read_pool_workers: int | None = None,  # ReadPoolConfig.unified_max_threads
    ):
        self.service = service
        self.security = security
        self._ssl_ctx = security.server_context() if security is not None else None
        self._sock = socket.create_server((host, port))
        self.addr = self._sock.getsockname()
        self._pool = ThreadPoolExecutor(max_workers=workers)
        # created lazily on the first read-method dispatch: PD / raft-only
        # servers never pay for read-pool threads
        self._read_pool: UnifiedReadPool | None = None
        self._read_pool_workers = read_pool_workers or workers
        self._read_pool_mu = threading.Lock()
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._pb_gateway_inst = None
        self._pb_gateway_mu = threading.Lock()

    def _pb_gateway(self):
        with self._pb_gateway_mu:
            if self._pb_gateway_inst is None:
                from .pb_gateway import PbGateway

                self._pb_gateway_inst = PbGateway(self.service)
            return self._pb_gateway_inst

    def _trace_root(self, method: str, request, t_dec: float, t_dec_end: float):
        """The request's root span, spanning decode→encode (docs/tracing.md):
        joins the trace the request context carries (forwarded hops and
        client-held traces propagate over the wire as plain context keys) or
        head-samples a fresh one.  The frame-decode stage — measured before
        any span could exist — lands as an explicitly-timed child."""
        ctx = None
        if isinstance(request, dict):
            c = request.get("context")
            if isinstance(c, dict) and c.get("trace_id"):
                ctx = c
        if ctx is None and not trace.enabled():
            return trace.NOOP
        root = trace.start_trace(
            f"rpc.{method}", ctx=ctx, start=t_dec, method=method,
            store=getattr(getattr(self.service, "read_plane", None),
                          "store_id", None) or "")
        if root:
            root.record("wire.decode", t_dec, t_dec_end)
        return root

    @property
    def read_pool(self) -> UnifiedReadPool:
        with self._read_pool_mu:
            if self._read_pool is None:
                if self._stop.is_set():
                    # a frame racing shutdown must not birth an unstoppable pool
                    raise RuntimeError("server is stopped")
                self._read_pool = UnifiedReadPool(
                    workers=self._read_pool_workers, name="unified-read-pool"
                )
            return self._read_pool

    def start(self) -> None:
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except TimeoutError:
                continue
            except OSError:
                return
            threading.Thread(target=self._handshake_and_serve, args=(conn,), daemon=True).start()

    def _handshake_and_serve(self, conn: socket.socket) -> None:
        if self._ssl_ctx is not None:
            try:
                conn = self._ssl_ctx.wrap_socket(conn, server_side=True)
                self.security.check_common_name(conn)
            except Exception:  # noqa: BLE001 — failed handshake, drop the peer
                conn.close()
                return
        self._serve_conn(conn)

    def _serve_conn(self, conn: socket.socket) -> None:
        send_mu = threading.Lock()
        # per-stream flow-control credits (the gRPC window role): a stream's
        # writer may have at most STREAM_WINDOW unacked frames in flight;
        # the client acks as its consumer drains, so memory is O(window)
        # on BOTH sides no matter how slow the consumer is
        stream_credits: dict[int, threading.Semaphore] = {}
        stream_cancelled: set[int] = set()
        conn_dead = threading.Event()
        try:
            while not self._stop.is_set():
                frame = read_frame(conn)
                if frame is None:
                    return
                t_dec = time.perf_counter()
                req_id, method, request = wire.loads(frame)
                t_dec_end = time.perf_counter()
                WIRE_STAGE.observe(t_dec_end - t_dec, stage="decode")

                if method == "_stream_ack":
                    sem = stream_credits.get(request.get("id"))
                    if sem is not None:
                        for _ in range(int(request.get("n", 1))):
                            sem.release()
                    continue
                if method == "_stream_cancel":
                    sid = request.get("id")
                    # record the cancel even when the stream's writer has
                    # not registered yet (request still queued in the pool):
                    # the writer checks this set right after registering
                    stream_cancelled.add(sid)
                    sem = stream_credits.get(sid)
                    if sem is not None:
                        sem.release()  # wake the parked writer to notice
                    continue

                if req_id == 0:
                    # oneway frame (peer raft traffic): no response, and run
                    # INLINE so frames keep the connection's FIFO order —
                    # snapshot chunks and raft messages must not be reordered
                    # by pool scheduling (the reference's peer stream is
                    # likewise ordered per connection)
                    try:
                        self.service.dispatch(method, request)
                    except Exception:  # noqa: BLE001 — lossy channel
                        pass
                    continue

                t_submit = time.perf_counter()
                # request-root span (docs/tracing.md): joins the trace the
                # context carries (forwarded hops, client-initiated traces)
                # or head-samples a fresh one; the wire stages land as child
                # spans mirroring the WIRE_STAGE histogram.  One branch and
                # no allocation when tracing is off and no ctx carries a
                # trace id.
                root = self._trace_root(method, request, t_dec, t_dec_end)

                def run(req_id=req_id, method=method, request=request,
                        t_submit=t_submit, root=root, t_dec_end=t_dec_end):
                    t0 = time.perf_counter()
                    # route = pool queue wait: submission to handler start
                    WIRE_STAGE.observe(t0 - t_submit, stage="route")
                    if root:
                        # the span tiles the root exactly: decode-end to
                        # handler start is ALL routing overhead (trace/
                        # closure bookkeeping + pool queue wait), so the
                        # stage spans account for the whole request
                        root.record("wire.route", t_dec_end, t0)
                    try:
                        with root.active(), trace.span("wire.execute"):
                            if method.startswith("pb/"):
                                # kvproto mode: request/response are protobuf
                                # bytes (pb_gateway), framing unchanged
                                resp = self._pb_gateway().handle(method[3:], request)
                            else:
                                resp = self.service.dispatch(method, request)
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        resp = {"error": {"other": repr(e), "code": error_code.code_of(e)}}
                    GRPC_MSG_TOTAL.inc(method=method)
                    t_done = time.perf_counter()
                    GRPC_MSG_DURATION.observe(t_done - t0, method=method)
                    WIRE_STAGE.observe(t_done - t0, stage="execute")
                    if isinstance(resp, dict) and resp.get("error"):
                        GRPC_MSG_FAIL.inc(method=method)
                    if inspect.isgenerator(resp) and root:
                        # streaming responses finish the root HERE: the
                        # per-frame credit loop below has early-return paths
                        # (consumer gone/cancelled) that must not leak an
                        # open trace record
                        root.tag(streaming=True)
                        root.finish()
                        root = trace.NOOP
                    if inspect.isgenerator(resp):
                        # server-streaming response (endpoint.rs:508): one
                        # wire frame per yielded item, same req_id, closed by
                        # a stream_end frame.  send_mu is taken PER FRAME so
                        # a long stream interleaves with other responses on
                        # the connection; the credit window caps in-flight
                        # frames so neither side buffers more than O(window).
                        sem = threading.Semaphore(STREAM_WINDOW)
                        stream_credits[req_id] = sem
                        final = {"stream_end": True}
                        try:
                            if req_id in stream_cancelled:
                                return  # cancelled before we even started
                            for item in resp:
                                # bounded park: a consumer that neither acks
                                # nor cancels must not pin this pool worker
                                # forever (STREAM_IDLE_TIMEOUT)
                                stalled = 0.0
                                while not sem.acquire(timeout=1.0):
                                    stalled += 1.0
                                    if (conn_dead.is_set() or self._stop.is_set()
                                            or stalled >= STREAM_IDLE_TIMEOUT):
                                        return  # consumer gone; drop stream
                                if req_id in stream_cancelled:
                                    return  # consumer abandoned the stream
                                parts = wire.dumps_parts([req_id, {"stream": item}])
                                with send_mu:
                                    # lint: allow(lock-blocking-call) -- send_mu
                                    # guards exactly this socket: frames from
                                    # concurrent handlers must not interleave
                                    write_frame_parts(conn, parts)
                        except OSError:
                            return  # client went away mid-stream
                        except Exception as e:  # noqa: BLE001 — wire boundary
                            final["error"] = {"other": repr(e),
                                              "code": error_code.code_of(e)}
                        finally:
                            stream_credits.pop(req_id, None)
                            stream_cancelled.discard(req_id)
                        resp = final
                    # single-buffer response assembly: dumps_parts emits the
                    # response's large bytes payloads (coprocessor chunk
                    # data) as passthrough buffers and the frame writer
                    # gather-writes them — no re-encoding copy of the data
                    t_enc = time.perf_counter()
                    parts = wire.dumps_parts([req_id, resp])
                    with send_mu:
                        try:
                            # lint: allow(lock-blocking-call) -- per-socket
                            # frame serialization (same as the stream path)
                            write_frame_parts(conn, parts)
                        except OSError:
                            pass
                    t_enc_end = time.perf_counter()
                    WIRE_STAGE.observe(t_enc_end - t_enc, stage="encode")
                    if root:
                        # execute-end to send-done: response assembly +
                        # frame write (tiles the root, see wire.route)
                        root.record("wire.encode", t_done, t_enc_end)
                        root.finish(end=t_enc_end)

                if method.removeprefix("pb/") in _READ_METHODS:
                    ctx, group = {}, id(conn)
                    prio_hint = None
                    if isinstance(request, dict):
                        c = request.get("context")
                        ctx = c if isinstance(c, dict) else {}
                        # group by caller txn (start_ts); falls back per-conn
                        group = ctx.get("resource_group") or request.get("start_ts") or id(conn)
                    elif isinstance(request, bytes):
                        # pb mode: peek at Context (task_id, priority) without
                        # a full request decode
                        from .pb_gateway import sched_hints

                        g, prio_hint = sched_hints(request)
                        group = g or id(conn)
                    prio = (
                        TaskPriority.HIGH
                        if ctx.get("priority") == "high" or prio_hint == "high"
                        else TaskPriority.NORMAL
                    )
                    try:
                        self.read_pool.submit(run, group=group, priority=prio)
                    except RuntimeError:  # pool/server stopped mid-shutdown
                        root.finish()
                        return
                else:
                    try:
                        self._pool.submit(run)
                    except RuntimeError:  # executor shut down mid-frame
                        root.finish()
                        return
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            conn_dead.set()  # wake any stream writer parked on credits
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        self._sock.close()
        self._pool.shutdown(wait=False)
        with self._read_pool_mu:
            if self._read_pool is not None:
                self._read_pool.stop()


_STREAM_DEAD = object()  # sentinel: connection died under an open stream


class Client:
    """Blocking client with request multiplexing (ReqBatcher flavor)."""

    def __init__(self, host: str, port: int, security=None):
        self._sock = socket.create_connection((host, port))
        if security is not None and security.enabled:
            self._sock = security.client_context().wrap_socket(self._sock)
        self._dead = False
        self._mu = threading.Lock()
        # writes serialize separately from bookkeeping: concurrent callers
        # interleaving sendall bytes mid-frame would desync the server
        self._send_mu = threading.Lock()
        self._next_id = 0
        self._pending: dict[int, threading.Event] = {}
        self._results: dict[int, object] = {}
        # server-streaming calls: req_id -> bounded frame queue; the reader
        # pushes each same-id frame, the consumer iterates (call_stream)
        self._streams: dict[int, queue.Queue] = {}
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                frame = read_frame(self._sock)
                if frame is None:
                    return
                req_id, resp = wire.loads(frame)
                with self._mu:
                    q = self._streams.get(req_id)
                    if q is not None:
                        if isinstance(resp, dict) and resp.get("stream_end"):
                            del self._streams[req_id]
                        q.put(resp)
                        continue
                    ev = self._pending.pop(req_id, None)
                    if ev is None:
                        continue  # late frame for a cancelled/timed-out call
                    self._results[req_id] = resp
                ev.set()
        except (ConnectionError, OSError, ValueError):
            with self._mu:
                self._dead = True
                for ev in self._pending.values():
                    ev.set()
                self._pending.clear()
                for q in self._streams.values():
                    q.put(_STREAM_DEAD)
                self._streams.clear()

    def call(self, method: str, request: dict, timeout: float = 30.0):
        with self._mu:
            if self._dead:
                raise ConnectionError("connection is closed")
            self._next_id += 1
            req_id = self._next_id
            ev = threading.Event()
            self._pending[req_id] = ev
        with self._send_mu:
            # lint: allow(lock-blocking-call) -- _send_mu exists to serialize
            # frames on this client's one socket
            write_frame(self._sock, wire.dumps([req_id, method, request]))
        if not ev.wait(timeout):
            with self._mu:
                # deregister so a late response is dropped, not leaked
                self._pending.pop(req_id, None)
                self._results.pop(req_id, None)
            raise TimeoutError(f"{method} timed out")
        with self._mu:
            if req_id not in self._results:
                raise ConnectionError(f"connection lost during {method}")
            return self._results.pop(req_id)

    def call_stream(self, method: str, request: dict, timeout: float = 30.0):
        """Server-streaming call: returns an iterator yielding each streamed
        item as the server produces it (kv.rs coprocessor_stream:574).  The
        request is sent EAGERLY (before the first next()); in-flight frames
        are capped by the server-side credit window, and the final
        stream_end frame may carry a mid-stream execution error, raised on
        the consumer."""
        with self._mu:
            if self._dead:
                raise ConnectionError("connection is closed")
            self._next_id += 1
            req_id = self._next_id
            q: queue.Queue = queue.Queue()
            self._streams[req_id] = q
        with self._send_mu:
            # lint: allow(lock-blocking-call) -- per-socket frame serialization
            write_frame(self._sock, wire.dumps([req_id, method, request]))
        return self._stream_iter(method, req_id, q, timeout)

    def _stream_iter(self, method: str, req_id: int, q: "queue.Queue", timeout: float):
        finished = False
        try:
            while True:
                try:
                    resp = q.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError(f"{method} stream timed out") from None
                if resp is _STREAM_DEAD:
                    finished = True
                    raise ConnectionError(f"connection lost during {method}")
                if isinstance(resp, dict) and resp.get("stream_end"):
                    finished = True
                    if resp.get("error"):
                        raise RuntimeError(f"{method} failed mid-stream: {resp['error']}")
                    return
                if isinstance(resp, dict) and "stream" in resp:
                    yield resp["stream"]
                    # consumer drained one frame: grant the server one
                    # credit (oneway ack — no response expected)
                    try:
                        with self._send_mu:
                            # lint: allow(lock-blocking-call) -- per-socket
                            # frame serialization
                            write_frame(self._sock, wire.dumps(
                                [0, "_stream_ack", {"id": req_id, "n": 1}]))
                    except OSError:
                        finished = True
                        raise ConnectionError(f"connection lost during {method}")
                else:
                    # unary shape: pre-stream validation error (or a non-
                    # streaming server) — no stream_end will follow, so the
                    # registration must be dropped here, not by _read_loop
                    finished = True
                    with self._mu:
                        self._streams.pop(req_id, None)
                    if isinstance(resp, dict) and resp.get("error"):
                        raise RuntimeError(f"{method} failed: {resp['error']}")
                    yield resp
                    return
        finally:
            if not finished:
                # consumer abandoned the stream early: tell the server so
                # its writer doesn't stay parked waiting for credits
                with self._mu:
                    self._streams.pop(req_id, None)
                try:
                    with self._send_mu:
                        # lint: allow(lock-blocking-call) -- per-socket frame
                        # serialization
                        write_frame(self._sock, wire.dumps(
                            [0, "_stream_cancel", {"id": req_id}]))
                except OSError:
                    pass

    def close(self) -> None:
        self._sock.close()
