"""TCP server: the node's RPC front door.

Re-expression of ``src/server/server.rs`` + the ``batch_commands`` stream
(service/kv.rs:891): one socket per client, length-prefixed frames, each frame
``[req_id, method, request]`` (wire codec) answered out of order —
multiplexed like batch_commands.  A thread-pool executes handlers so slow
commands don't block the socket reader.
"""

from __future__ import annotations

import socket
import struct
import threading
from concurrent.futures import ThreadPoolExecutor

from ..util import error_code
from ..util.worker import TaskPriority, UnifiedReadPool
from . import wire
from .service import KvService

error_code.register_builtin()

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 << 20

# read-path RPCs go through the unified read pool (src/read_pool.rs routes
# point gets / scans / coprocessor there); writes keep the plain executor so
# a saturated analytical workload can't starve the write path's threads
_READ_METHODS = (
    "kv_get", "kv_batch_get", "kv_scan", "kv_scan_lock",
    "raw_get", "raw_batch_get", "raw_scan", "raw_batch_scan", "raw_get_key_ttl",
    "coprocessor", "coprocessor_stream", "raw_coprocessor",
    "mvcc_get_by_key", "mvcc_get_by_start_ts",
)


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> bytes | None:
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > _MAX_FRAME:
        raise ValueError("frame too large")
    return _read_exact(sock, n)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


class Server:
    def __init__(
        self,
        service: KvService,
        host: str = "127.0.0.1",
        port: int = 0,
        workers: int = 8,
        security=None,
        read_pool_workers: int | None = None,  # ReadPoolConfig.unified_max_threads
    ):
        self.service = service
        self.security = security
        self._ssl_ctx = security.server_context() if security is not None else None
        self._sock = socket.create_server((host, port))
        self.addr = self._sock.getsockname()
        self._pool = ThreadPoolExecutor(max_workers=workers)
        # created lazily on the first read-method dispatch: PD / raft-only
        # servers never pay for read-pool threads
        self._read_pool: UnifiedReadPool | None = None
        self._read_pool_workers = read_pool_workers or workers
        self._read_pool_mu = threading.Lock()
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._pb_gateway_inst = None
        self._pb_gateway_mu = threading.Lock()

    def _pb_gateway(self):
        with self._pb_gateway_mu:
            if self._pb_gateway_inst is None:
                from .pb_gateway import PbGateway

                self._pb_gateway_inst = PbGateway(self.service)
            return self._pb_gateway_inst

    @property
    def read_pool(self) -> UnifiedReadPool:
        with self._read_pool_mu:
            if self._read_pool is None:
                if self._stop.is_set():
                    # a frame racing shutdown must not birth an unstoppable pool
                    raise RuntimeError("server is stopped")
                self._read_pool = UnifiedReadPool(
                    workers=self._read_pool_workers, name="unified-read-pool"
                )
            return self._read_pool

    def start(self) -> None:
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except TimeoutError:
                continue
            except OSError:
                return
            threading.Thread(target=self._handshake_and_serve, args=(conn,), daemon=True).start()

    def _handshake_and_serve(self, conn: socket.socket) -> None:
        if self._ssl_ctx is not None:
            try:
                conn = self._ssl_ctx.wrap_socket(conn, server_side=True)
                self.security.check_common_name(conn)
            except Exception:  # noqa: BLE001 — failed handshake, drop the peer
                conn.close()
                return
        self._serve_conn(conn)

    def _serve_conn(self, conn: socket.socket) -> None:
        send_mu = threading.Lock()
        try:
            while not self._stop.is_set():
                frame = read_frame(conn)
                if frame is None:
                    return
                req_id, method, request = wire.loads(frame)

                if req_id == 0:
                    # oneway frame (peer raft traffic): no response, and run
                    # INLINE so frames keep the connection's FIFO order —
                    # snapshot chunks and raft messages must not be reordered
                    # by pool scheduling (the reference's peer stream is
                    # likewise ordered per connection)
                    try:
                        self.service.dispatch(method, request)
                    except Exception:  # noqa: BLE001 — lossy channel
                        pass
                    continue

                def run(req_id=req_id, method=method, request=request):
                    try:
                        if method.startswith("pb/"):
                            # kvproto mode: request/response are protobuf
                            # bytes (pb_gateway), framing unchanged
                            resp = self._pb_gateway().handle(method[3:], request)
                        else:
                            resp = self.service.dispatch(method, request)
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        resp = {"error": {"other": repr(e), "code": error_code.code_of(e)}}
                    payload = wire.dumps([req_id, resp])
                    with send_mu:
                        try:
                            write_frame(conn, payload)
                        except OSError:
                            pass

                if method.removeprefix("pb/") in _READ_METHODS:
                    ctx, group = {}, id(conn)
                    prio_hint = None
                    if isinstance(request, dict):
                        c = request.get("context")
                        ctx = c if isinstance(c, dict) else {}
                        # group by caller txn (start_ts); falls back per-conn
                        group = ctx.get("resource_group") or request.get("start_ts") or id(conn)
                    elif isinstance(request, bytes):
                        # pb mode: peek at Context (task_id, priority) without
                        # a full request decode
                        from .pb_gateway import sched_hints

                        g, prio_hint = sched_hints(request)
                        group = g or id(conn)
                    prio = (
                        TaskPriority.HIGH
                        if ctx.get("priority") == "high" or prio_hint == "high"
                        else TaskPriority.NORMAL
                    )
                    try:
                        self.read_pool.submit(run, group=group, priority=prio)
                    except RuntimeError:  # pool/server stopped mid-shutdown
                        return
                else:
                    try:
                        self._pool.submit(run)
                    except RuntimeError:  # executor shut down mid-frame
                        return
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        self._sock.close()
        self._pool.shutdown(wait=False)
        with self._read_pool_mu:
            if self._read_pool is not None:
                self._read_pool.stop()


class Client:
    """Blocking client with request multiplexing (ReqBatcher flavor)."""

    def __init__(self, host: str, port: int, security=None):
        self._sock = socket.create_connection((host, port))
        if security is not None and security.enabled:
            self._sock = security.client_context().wrap_socket(self._sock)
        self._dead = False
        self._mu = threading.Lock()
        # writes serialize separately from bookkeeping: concurrent callers
        # interleaving sendall bytes mid-frame would desync the server
        self._send_mu = threading.Lock()
        self._next_id = 0
        self._pending: dict[int, threading.Event] = {}
        self._results: dict[int, object] = {}
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                frame = read_frame(self._sock)
                if frame is None:
                    return
                req_id, resp = wire.loads(frame)
                with self._mu:
                    self._results[req_id] = resp
                    ev = self._pending.pop(req_id, None)
                if ev is not None:
                    ev.set()
        except (ConnectionError, OSError, ValueError):
            with self._mu:
                self._dead = True
                for ev in self._pending.values():
                    ev.set()
                self._pending.clear()

    def call(self, method: str, request: dict, timeout: float = 30.0):
        with self._mu:
            if self._dead:
                raise ConnectionError("connection is closed")
            self._next_id += 1
            req_id = self._next_id
            ev = threading.Event()
            self._pending[req_id] = ev
        with self._send_mu:
            write_frame(self._sock, wire.dumps([req_id, method, request]))
        if not ev.wait(timeout):
            raise TimeoutError(f"{method} timed out")
        with self._mu:
            if req_id not in self._results:
                raise ConnectionError(f"connection lost during {method}")
            return self._results.pop(req_id)

    def close(self) -> None:
        self._sock.close()
