"""kvproto protobuf gateway: the reference's external wire contract.

Adapts protobuf request/response pairs (proto.kvproto_pb — the messages the
reference's gRPC service speaks, src/server/service/kv.rs:129-303) onto the
in-process ``KvService`` dict dispatch.  The transport stays this framework's
length-framed TCP (SURVEY §2 "protocol crates" note); what rides it for a
protobuf-mode peer is kvproto bytes:

    frame = method name + kvproto Request bytes  ->  kvproto Response bytes

Coprocessor requests carry a real ``tipb.DAGRequest`` in ``Request.data`` and
return ``tipb.SelectResponse`` bytes in ``Response.data`` via copr.tipb_bridge.
"""

from __future__ import annotations

from ..proto import kvproto_pb as kp
from ..proto import tipb_pb as tp


class PbGatewayError(ValueError):
    pass


# ---------------------------------------------------------------------------
# shared converters
# ---------------------------------------------------------------------------

_OP_TO_WIRE = {
    kp.Op.Put: "put",
    kp.Op.Del: "delete",
    kp.Op.Lock: "lock",
    kp.Op.CheckNotExists: "check_not_exists",
    6: "insert",  # kvrpcpb Op::Insert
}


def ctx_to_dict(ctx: kp.Context | None) -> dict:
    if ctx is None:
        return {}
    out = {"region_id": ctx.region_id, "term": ctx.term}
    if ctx.region_epoch is not None:
        out["region_epoch"] = {
            "conf_ver": ctx.region_epoch.conf_ver,
            "version": ctx.region_epoch.version,
        }
    if ctx.peer is not None:
        out["peer"] = {"id": ctx.peer.id, "store_id": ctx.peer.store_id}
    if ctx.replica_read:
        out["replica_read"] = True
    if ctx.stale_read:
        out["stale_read"] = True
    if ctx.priority == kp.CommandPri.High:
        out["priority"] = "high"
    elif ctx.priority == kp.CommandPri.Low:
        out["priority"] = "low"
    if ctx.task_id:
        out["resource_group"] = ctx.task_id
    return out


def sched_hints(payload: bytes) -> tuple[object | None, str | None]:
    """Cheap pre-dispatch peek at a kvproto request's Context for read-pool
    scheduling (group, priority) — parses only the leading context field."""
    try:
        from ..proto.wire import read_varint

        key, pos = read_varint(payload, 0)
        if key != (1 << 3) | 2:  # field 1, LEN = Context on every request
            return None, None
        ln, pos = read_varint(payload, pos)
        ctx = kp.Context.decode(payload[pos:pos + ln])
        group = ctx.task_id or None
        prio = "high" if ctx.priority == kp.CommandPri.High else None
        return group, prio
    except Exception:  # noqa: BLE001 — scheduling hint only, never fail a frame
        return None, None


def _key_error(err: dict) -> kp.KeyError:
    ke = kp.KeyError()
    if "locked" in err:
        l = err["locked"]
        ke.locked = kp.LockInfo(
            primary_lock=l.get("primary", b""),
            lock_version=l.get("lock_ts", 0),
            key=l.get("key", b""),
            lock_ttl=l.get("ttl", 0),
        )
    elif "conflict" in err:
        c = err["conflict"]
        ke.conflict = kp.WriteConflict(
            start_ts=c.get("start_ts", 0),
            conflict_ts=c.get("conflict_start_ts", 0),
            conflict_commit_ts=c.get("conflict_commit_ts", 0),
            key=c.get("key", b""),
        )
    elif "already_exists" in err:
        ke.already_exist = kp.AlreadyExist(key=err["already_exists"].get("key", b""))
    elif "deadlock" in err:
        d = err["deadlock"]
        ke.deadlock = kp.Deadlock(
            lock_ts=d.get("blocked_on_txn", 0),
            deadlock_key_hash=abs(hash(tuple(d.get("cycle", ())))) & (1 << 63) - 1,
        )
    else:
        ke.abort = str(err.get("other", err))
    return ke


def _region_error(err: dict) -> kp.RegionError | None:
    if "not_leader" in err:
        nl = err["not_leader"]
        out = kp.RegionError(message="not leader")
        leader_store = nl.get("leader_store")
        out.not_leader = kp.NotLeader(region_id=nl.get("region_id", 0) or 0)
        if leader_store:
            out.not_leader.leader = kp.Peer(store_id=leader_store)
        return out
    if "epoch_not_match" in err:
        return kp.RegionError(message="epoch not match", epoch_not_match=kp.EpochNotMatch())
    if "region_not_found" in err:
        return kp.RegionError(
            message="region not found",
            region_not_found=kp.RegionNotFound(region_id=err["region_not_found"].get("region_id", 0)),
        )
    if "data_not_ready" in err:
        dnr = err["data_not_ready"]
        # safe_ts on the wire = the highest ts this replica CAN serve: the
        # refusal's resolved watermark (or the store floor hint when the
        # read plane enriched the error) — what a kvproto client lowers its
        # stale read_ts to (docs/stale_reads.md)
        safe = dnr.get("resolved") or dnr.get("safe_ts") or 0
        return kp.RegionError(
            message="data is not ready",
            data_is_not_ready=kp.DataIsNotReady(
                region_id=dnr.get("region_id", 0) or 0, safe_ts=safe),
        )
    return None


def _apply_error(resp, err: dict | None, key_error_field: str = "error",
                 repeated: bool = False) -> None:
    if not err:
        return
    re = _region_error(err)
    if re is not None:
        resp.region_error = re
        return
    ke = _key_error(err)
    if repeated:
        getattr(resp, key_error_field).append(ke)
    else:
        setattr(resp, key_error_field, ke)


def _pairs(pairs) -> list[kp.KvPair]:
    return [kp.KvPair(key=k, value=v) for k, v in pairs]


# ---------------------------------------------------------------------------
# per-RPC converters: (ReqCls, to_dict, RespCls, fill_resp)
# ---------------------------------------------------------------------------

def _r_get(q: kp.GetRequest) -> dict:
    return {"key": q.key, "version": q.version, "context": ctx_to_dict(q.context),
            "bypass_locks": list(q.context.resolved_locks) if q.context else []}


def _w_get(r: dict) -> kp.GetResponse:
    out = kp.GetResponse()
    _apply_error(out, r.get("error"))
    if r.get("value") is not None:
        out.value = r["value"]
    if r.get("not_found"):
        out.not_found = True
    return out


def _r_scan(q: kp.ScanRequest) -> dict:
    return {
        "start_key": q.start_key, "end_key": q.end_key or None,
        "limit": q.limit or None, "version": q.version,
        "key_only": q.key_only, "reverse": q.reverse,
        "context": ctx_to_dict(q.context),
    }


def _w_scan(r: dict) -> kp.ScanResponse:
    out = kp.ScanResponse()
    _apply_error(out, r.get("error"))
    out.pairs = _pairs(r.get("pairs", []))
    return out


def _r_prewrite(q: kp.PrewriteRequest) -> dict:
    muts = []
    for m in q.mutations:
        op = _OP_TO_WIRE.get(m.op)
        if op is None:
            raise PbGatewayError(f"unsupported mutation op {m.op}")
        # empty bytes is a legal Put value (protobuf can't distinguish unset
        # from empty) — only valueless op kinds drop the field
        value = m.value if op in ("put", "insert") else None
        muts.append({"op": op, "key": m.key, "value": value})
    return {
        "mutations": muts,
        "primary_lock": q.primary_lock,
        "start_version": q.start_version,
        "lock_ttl": q.lock_ttl or 3000,
        "use_async_commit": q.use_async_commit,
        "secondaries": list(q.secondaries),
        "is_pessimistic": bool(q.for_update_ts),
        "is_pessimistic_lock": list(q.is_pessimistic_lock),
        "for_update_ts": q.for_update_ts,
        "context": ctx_to_dict(q.context),
    }


def _w_prewrite(r: dict) -> kp.PrewriteResponse:
    out = kp.PrewriteResponse()
    if r.get("errors"):
        for e in r["errors"]:
            _apply_error(out, e, "errors", repeated=True)
    elif r.get("error"):
        _apply_error(out, r["error"], "errors", repeated=True)
    if r.get("min_commit_ts"):
        out.min_commit_ts = r["min_commit_ts"]
    return out


def _r_commit(q: kp.CommitRequest) -> dict:
    return {"keys": list(q.keys), "start_version": q.start_version,
            "commit_version": q.commit_version, "context": ctx_to_dict(q.context)}


def _w_commit(r: dict) -> kp.CommitResponse:
    out = kp.CommitResponse()
    _apply_error(out, r.get("error"))
    if r.get("commit_version"):
        out.commit_version = r["commit_version"]
    return out


def _r_batch_get(q: kp.BatchGetRequest) -> dict:
    return {"keys": list(q.keys), "version": q.version, "context": ctx_to_dict(q.context)}


def _w_batch_get(r: dict) -> kp.BatchGetResponse:
    out = kp.BatchGetResponse()
    _apply_error(out, r.get("error"))
    out.pairs = _pairs(r.get("pairs", []))
    return out


def _r_batch_rollback(q: kp.BatchRollbackRequest) -> dict:
    return {"keys": list(q.keys), "start_version": q.start_version,
            "context": ctx_to_dict(q.context)}


def _w_simple_keyerr(cls):
    def w(r: dict):
        out = cls()
        _apply_error(out, r.get("error"))
        return out
    return w


def _r_cleanup(q: kp.CleanupRequest) -> dict:
    return {"key": q.key, "start_version": q.start_version,
            "current_ts": q.current_ts, "context": ctx_to_dict(q.context)}


def _w_cleanup(r: dict) -> kp.CleanupResponse:
    out = kp.CleanupResponse()
    _apply_error(out, r.get("error"))
    if r.get("commit_version"):
        out.commit_version = r["commit_version"]
    return out


def _r_pessimistic_lock(q: kp.PessimisticLockRequest) -> dict:
    return {
        "keys": [m.key for m in q.mutations],
        "primary_lock": q.primary_lock,
        "start_version": q.start_version,
        "for_update_ts": q.for_update_ts,
        "lock_ttl": q.lock_ttl or 3000,
        "return_values": q.return_values,
        # WaitTimeout::from_encoded (reference): 0 = no wait, <0 = default
        # wait (wait-for-lock-timeout, 1s), >0 = that many ms
        "wait_timeout_ms": 1000 if q.wait_timeout < 0 else q.wait_timeout,
        "context": ctx_to_dict(q.context),
    }


def _w_pessimistic_lock(r: dict) -> kp.PessimisticLockResponse:
    out = kp.PessimisticLockResponse()
    if r.get("error"):
        _apply_error(out, r["error"], "errors", repeated=True)
    vals = r.get("values")
    if vals:
        out.values = [v if v is not None else b"" for v in vals]
        out.not_founds = [v is None for v in vals]
    return out


def _r_pessimistic_rollback(q: kp.PessimisticRollbackRequest) -> dict:
    return {"keys": list(q.keys), "start_version": q.start_version,
            "for_update_ts": q.for_update_ts, "context": ctx_to_dict(q.context)}


def _w_pessimistic_rollback(r: dict) -> kp.PessimisticRollbackResponse:
    out = kp.PessimisticRollbackResponse()
    if r.get("error"):
        _apply_error(out, r["error"], "errors", repeated=True)
    return out


def _r_txn_heart_beat(q: kp.TxnHeartBeatRequest) -> dict:
    return {"primary_lock": q.primary_lock, "start_version": q.start_version,
            "advise_lock_ttl": q.advise_lock_ttl, "context": ctx_to_dict(q.context)}


def _w_txn_heart_beat(r: dict) -> kp.TxnHeartBeatResponse:
    out = kp.TxnHeartBeatResponse()
    _apply_error(out, r.get("error"))
    if r.get("lock_ttl"):
        out.lock_ttl = r["lock_ttl"]
    return out


# check_txn_status kind -> kvrpcpb Action (reference maps TxnStatus to action)
_KIND_TO_ACTION = {
    "ttl_expire_rollback": kp.Action.TTLExpireRollback,
    "lock_not_exist_rollback": kp.Action.LockNotExistRollback,
    "min_commit_ts_pushed": kp.Action.MinCommitTSPushed,
    "lock_not_exist_do_nothing": kp.Action.LockNotExistDoNothing,
}


def _r_check_txn_status(q: kp.CheckTxnStatusRequest) -> dict:
    return {
        "primary_key": q.primary_key, "lock_ts": q.lock_ts,
        "caller_start_ts": q.caller_start_ts, "current_ts": q.current_ts,
        "rollback_if_not_exist": q.rollback_if_not_exist,
        "force_sync_commit": q.force_sync_commit,
        "context": ctx_to_dict(q.context),
    }


def _w_check_txn_status(r: dict) -> kp.CheckTxnStatusResponse:
    out = kp.CheckTxnStatusResponse()
    _apply_error(out, r.get("error"))
    if r.get("lock_ttl"):
        out.lock_ttl = r["lock_ttl"]
    if r.get("commit_version"):
        out.commit_version = r["commit_version"]
    action = _KIND_TO_ACTION.get(r.get("kind"))
    if action:
        out.action = action
    return out


def _r_check_secondary(q: kp.CheckSecondaryLocksRequest) -> dict:
    return {"keys": list(q.keys), "start_version": q.start_version,
            "context": ctx_to_dict(q.context)}


def _w_check_secondary(r: dict) -> kp.CheckSecondaryLocksResponse:
    out = kp.CheckSecondaryLocksResponse()
    _apply_error(out, r.get("error"))
    out.locks = [kp.LockInfo(lock_version=l["ts"], primary_lock=l["primary"])
                 for l in r.get("locks", [])]
    if r.get("commit_ts"):
        out.commit_ts = r["commit_ts"]
    return out


def _r_scan_lock(q: kp.ScanLockRequest) -> dict:
    return {"start_key": q.start_key or None, "end_key": q.end_key or None,
            "max_version": q.max_version, "limit": q.limit or None,
            "context": ctx_to_dict(q.context)}


def _w_scan_lock(r: dict) -> kp.ScanLockResponse:
    out = kp.ScanLockResponse()
    _apply_error(out, r.get("error"))
    out.locks = [
        kp.LockInfo(key=l["key"], primary_lock=l["primary"],
                    lock_version=l["lock_version"], lock_ttl=l.get("ttl", 0))
        for l in r.get("locks", [])
    ]
    return out


def _r_resolve_lock(q: kp.ResolveLockRequest) -> dict:
    return {"start_version": q.start_version, "commit_version": q.commit_version,
            "keys": list(q.keys) or None, "context": ctx_to_dict(q.context)}


def _r_delete_range(q: kp.DeleteRangeRequest) -> dict:
    return {"start_key": q.start_key, "end_key": q.end_key,
            "context": ctx_to_dict(q.context)}


def _w_delete_range(r: dict) -> kp.DeleteRangeResponse:
    out = kp.DeleteRangeResponse()
    err = r.get("error")
    if err:
        re = _region_error(err)
        if re is not None:
            out.region_error = re
        else:
            out.error = str(err.get("other", err))
    return out


# -- raw KV -----------------------------------------------------------------

def _raw_err(out, r: dict):
    err = r.get("error")
    if err:
        re = _region_error(err)
        if re is not None:
            out.region_error = re
        else:
            out.error = str(err.get("other", err))
    return out


def _r_raw_get(q: kp.RawGetRequest) -> dict:
    return {"key": q.key, "context": ctx_to_dict(q.context)}


def _w_raw_get(r: dict) -> kp.RawGetResponse:
    out = _raw_err(kp.RawGetResponse(), r)
    if r.get("value") is not None:
        out.value = r["value"]
    if r.get("not_found"):
        out.not_found = True
    return out


def _r_raw_put(q: kp.RawPutRequest) -> dict:
    return {"key": q.key, "value": q.value, "ttl": q.ttl,
            "context": ctx_to_dict(q.context)}


def _r_raw_delete(q: kp.RawDeleteRequest) -> dict:
    return {"key": q.key, "context": ctx_to_dict(q.context)}


def _r_raw_scan(q: kp.RawScanRequest) -> dict:
    return {"start_key": q.start_key, "end_key": q.end_key or None,
            "limit": q.limit or None, "key_only": q.key_only,
            "reverse": q.reverse, "context": ctx_to_dict(q.context)}


def _w_raw_scan(r: dict) -> kp.RawScanResponse:
    out = _raw_err(kp.RawScanResponse(), r)
    out.kvs = _pairs(r.get("kvs", []))
    return out


def _r_raw_batch_get(q: kp.RawBatchGetRequest) -> dict:
    return {"keys": list(q.keys), "context": ctx_to_dict(q.context)}


def _w_raw_batch_get(r: dict) -> kp.RawBatchGetResponse:
    out = _raw_err(kp.RawBatchGetResponse(), r)
    out.pairs = _pairs(r.get("pairs", []))
    return out


def _r_raw_batch_put(q: kp.RawBatchPutRequest) -> dict:
    return {"pairs": [(p.key, p.value) for p in q.pairs], "ttl": q.ttl,
            "context": ctx_to_dict(q.context)}


def _r_raw_batch_delete(q: kp.RawBatchDeleteRequest) -> dict:
    return {"keys": list(q.keys), "context": ctx_to_dict(q.context)}


def _r_raw_delete_range(q: kp.RawDeleteRangeRequest) -> dict:
    return {"start_key": q.start_key, "end_key": q.end_key,
            "context": ctx_to_dict(q.context)}


def _r_raw_cas(q: kp.RawCasRequest) -> dict:
    return {
        "key": q.key, "value": q.value,
        "previous_value": None if q.previous_not_exist else q.previous_value,
        "ttl": q.ttl, "context": ctx_to_dict(q.context),
    }


def _w_raw_cas(r: dict) -> kp.RawCasResponse:
    out = _raw_err(kp.RawCasResponse(), r)
    out.succeed = bool(r.get("succeed"))
    prev = r.get("previous_value")
    if prev is None:
        out.previous_not_exist = True
    else:
        out.previous_value = prev
    return out


def _r_raw_get_key_ttl(q: kp.RawGetKeyTtlRequest) -> dict:
    return {"key": q.key, "context": ctx_to_dict(q.context)}


def _w_raw_get_key_ttl(r: dict) -> kp.RawGetKeyTtlResponse:
    out = _raw_err(kp.RawGetKeyTtlResponse(), r)
    if r.get("ttl") is not None:
        out.ttl = r["ttl"]
    if r.get("not_found"):
        out.not_found = True
    return out


# -- MVCC debug -------------------------------------------------------------

def _mvcc_info(info: dict | None) -> kp.MvccInfo | None:
    if not info:
        return None
    out = kp.MvccInfo()
    lk = info.get("lock")
    if lk:
        out.lock = kp.MvccLock(start_ts=lk["start_ts"], primary=lk["primary"],
                               short_value=lk.get("short_value") or b"")
    out.writes = [
        kp.MvccWrite(start_ts=w["start_ts"], commit_ts=w["commit_ts"],
                     short_value=w.get("short_value") or b"")
        for w in info.get("writes", [])
    ]
    out.values = [kp.MvccValue(start_ts=v["start_ts"], value=v["value"])
                  for v in info.get("values", [])]
    return out


def _r_mvcc_by_key(q: kp.MvccGetByKeyRequest) -> dict:
    return {"key": q.key, "context": ctx_to_dict(q.context)}


def _w_mvcc_by_key(r: dict) -> kp.MvccGetByKeyResponse:
    out = kp.MvccGetByKeyResponse()
    if r.get("error"):
        out.error = str(r["error"].get("other", r["error"]))
    info = _mvcc_info(r.get("info"))
    if info is not None:
        out.info = info
    return out


def _r_mvcc_by_start_ts(q: kp.MvccGetByStartTsRequest) -> dict:
    return {"start_ts": q.start_ts, "context": ctx_to_dict(q.context)}


def _w_mvcc_by_start_ts(r: dict) -> kp.MvccGetByStartTsResponse:
    out = kp.MvccGetByStartTsResponse()
    if r.get("error"):
        out.error = str(r["error"].get("other", r["error"]))
    if r.get("key"):
        out.key = r["key"]
    info = _mvcc_info(r.get("info"))
    if info is not None:
        out.info = info
    return out


# -- coprocessor ------------------------------------------------------------

def _r_coprocessor(q: kp.CoprRequestPb) -> dict:
    from ..copr.tipb_bridge import decode_dag_request

    if q.tp != kp.REQ_DAG:
        raise PbGatewayError(f"unsupported coprocessor tp {q.tp}")
    dag, pb = decode_dag_request(q.data)
    return {
        "tp": q.tp,
        "dag": dag,
        "ranges": [(r.start, r.end) for r in q.ranges],
        "start_ts": q.start_ts or pb.start_ts_fallback,
        "context": ctx_to_dict(q.context),
        "_pb": pb,
    }


def _output_field_types(pb: tp.DAGRequest):
    """Output schema for TypeChunk encoding, derived from the plan like
    runner.rs: scan columns flow through Selection/TopN/Limit unchanged;
    aggregation outputs have no wire-declared types, so return None there
    (the response legally downgrades to TypeDefault, which is self-typed)."""
    from ..copr.tipb_bridge import field_type_from_pb

    schema = None
    for ex in pb.executors:
        if ex.tp == tp.ExecType.TypeTableScan:
            schema = [field_type_from_pb(c) for c in ex.tbl_scan.columns]
        elif ex.tp == tp.ExecType.TypeIndexScan:
            schema = [field_type_from_pb(c) for c in ex.idx_scan.columns]
        elif ex.tp in (tp.ExecType.TypeAggregation, tp.ExecType.TypeStreamAgg):
            return None
    if schema is None:
        return None
    offsets = list(pb.output_offsets) or range(len(schema))
    return [schema[i] for i in offsets]


def _r_deadlock(q: kp.DeadlockRequest) -> dict:
    """deadlock.proto DeadlockRequest → the detector service's dict shape
    (tp enum → the service's string tags).  The request entry rides the _pb
    side-channel so the response can echo it faithfully (the reference's
    DeadlockResponse carries the original entry + its key hash)."""
    tps = {kp.DEADLOCK_DETECT: "detect",
           kp.DEADLOCK_CLEAN_UP_WAIT_FOR: "clean_up_wait_for",
           kp.DEADLOCK_CLEAN_UP: "clean_up"}
    tp_name = tps.get(q.tp)
    if tp_name is None:
        raise PbGatewayError(f"unknown deadlock request tp {q.tp}")
    entry = q.entry
    if entry is None:
        # detect(0,0) would fabricate a txn-0 self-deadlock; reject instead
        raise PbGatewayError("deadlock request missing its WaitForEntry")
    out = {"tp": tp_name, "waiter_ts": entry.txn, "lock_ts": entry.wait_for_txn,
           "_pb": entry}
    if tp_name == "clean_up":
        out["txn_ts"] = entry.txn
    return out


def _w_deadlock(r: dict, entry: "kp.WaitForEntry | None" = None) -> kp.DeadlockResponse:
    if r.get("error") or r.get("not_leader"):
        # an empty DeadlockResponse reads as "edge registered, no cycle" —
        # a dropped edge must fail loudly, never silently
        raise PbGatewayError(
            f"deadlock detect not served: {r.get('error') or 'not the detector leader'}")
    out = kp.DeadlockResponse()
    dl = r.get("deadlock")
    if dl:
        # echo the REQUEST entry (with its key/key_hash) like the reference;
        # deadlock_key_hash identifies the conflicting lock the caller must
        # resolve — the waiter's own key hash is the closest we track
        out.entry = entry if entry is not None else kp.WaitForEntry(
            txn=dl["waiting_txn"], wait_for_txn=dl["blocked_on_txn"])
        out.deadlock_key_hash = out.entry.key_hash
        cycle = list(dl.get("cycle") or [])
        if len(cycle) >= 2:
            # cycle = [lock, ..., waiter]: consecutive edges + the closing
            # edge back to the head — no self-edges, nothing dropped
            out.wait_chain = [
                kp.WaitForEntry(txn=a, wait_for_txn=b)
                for a, b in zip(cycle, cycle[1:] + cycle[:1])
            ]
    return out


def _w_coprocessor(r: dict, pb: tp.DAGRequest | None = None) -> kp.CoprResponsePb:
    out = kp.CoprResponsePb()
    err = r.get("error")
    if err:
        re = _region_error(err)
        if re is not None:
            out.region_error = re
        elif "locked" in err:
            l = err["locked"]
            out.locked = kp.LockInfo(
                primary_lock=l.get("primary", b""), lock_version=l.get("lock_ts", 0),
                key=l.get("key", b""), lock_ttl=l.get("ttl", 0))
        else:
            out.other_error = str(err.get("other", err))
        return out
    from ..copr.tipb_bridge import internal_response_to_tipb

    encode_type = tp.EncodeType.TypeDefault
    field_types = None
    if pb is not None and pb.encode_type == tp.EncodeType.TypeChunk:
        field_types = _output_field_types(pb)
        if field_types is not None:
            encode_type = tp.EncodeType.TypeChunk
    out.data = internal_response_to_tipb(r["data"], encode_type, field_types)
    return out


HANDLERS: dict[str, tuple] = {
    "kv_get": (kp.GetRequest, _r_get, _w_get),
    "kv_scan": (kp.ScanRequest, _r_scan, _w_scan),
    "kv_prewrite": (kp.PrewriteRequest, _r_prewrite, _w_prewrite),
    "kv_commit": (kp.CommitRequest, _r_commit, _w_commit),
    "kv_batch_get": (kp.BatchGetRequest, _r_batch_get, _w_batch_get),
    "kv_batch_rollback": (kp.BatchRollbackRequest, _r_batch_rollback,
                          _w_simple_keyerr(kp.BatchRollbackResponse)),
    "kv_cleanup": (kp.CleanupRequest, _r_cleanup, _w_cleanup),
    "kv_pessimistic_lock": (kp.PessimisticLockRequest, _r_pessimistic_lock,
                            _w_pessimistic_lock),
    "kv_pessimistic_rollback": (kp.PessimisticRollbackRequest,
                                _r_pessimistic_rollback, _w_pessimistic_rollback),
    "kv_txn_heart_beat": (kp.TxnHeartBeatRequest, _r_txn_heart_beat, _w_txn_heart_beat),
    "kv_check_txn_status": (kp.CheckTxnStatusRequest, _r_check_txn_status,
                            _w_check_txn_status),
    "kv_check_secondary_locks": (kp.CheckSecondaryLocksRequest, _r_check_secondary,
                                 _w_check_secondary),
    "kv_scan_lock": (kp.ScanLockRequest, _r_scan_lock, _w_scan_lock),
    "kv_resolve_lock": (kp.ResolveLockRequest, _r_resolve_lock,
                        _w_simple_keyerr(kp.ResolveLockResponse)),
    "kv_delete_range": (kp.DeleteRangeRequest, _r_delete_range, _w_delete_range),
    "raw_get": (kp.RawGetRequest, _r_raw_get, _w_raw_get),
    "raw_put": (kp.RawPutRequest, _r_raw_put,
                lambda r: _raw_err(kp.RawPutResponse(), r)),
    "raw_delete": (kp.RawDeleteRequest, _r_raw_delete,
                   lambda r: _raw_err(kp.RawDeleteResponse(), r)),
    "raw_scan": (kp.RawScanRequest, _r_raw_scan, _w_raw_scan),
    "raw_batch_get": (kp.RawBatchGetRequest, _r_raw_batch_get, _w_raw_batch_get),
    "raw_batch_put": (kp.RawBatchPutRequest, _r_raw_batch_put,
                      lambda r: _raw_err(kp.RawBatchPutResponse(), r)),
    "raw_batch_delete": (kp.RawBatchDeleteRequest, _r_raw_batch_delete,
                         lambda r: _raw_err(kp.RawBatchDeleteResponse(), r)),
    "raw_delete_range": (kp.RawDeleteRangeRequest, _r_raw_delete_range,
                         lambda r: _raw_err(kp.RawDeleteRangeResponse(), r)),
    "raw_compare_and_swap": (kp.RawCasRequest, _r_raw_cas, _w_raw_cas),
    "raw_get_key_ttl": (kp.RawGetKeyTtlRequest, _r_raw_get_key_ttl,
                        _w_raw_get_key_ttl),
    "mvcc_get_by_key": (kp.MvccGetByKeyRequest, _r_mvcc_by_key, _w_mvcc_by_key),
    "mvcc_get_by_start_ts": (kp.MvccGetByStartTsRequest, _r_mvcc_by_start_ts,
                             _w_mvcc_by_start_ts),
    "coprocessor": (kp.CoprRequestPb, _r_coprocessor, _w_coprocessor),
    "deadlock_detect": (kp.DeadlockRequest, _r_deadlock, _w_deadlock),
}


RESPONSE_TYPES = {
    "kv_get": kp.GetResponse,
    "kv_scan": kp.ScanResponse,
    "kv_prewrite": kp.PrewriteResponse,
    "kv_commit": kp.CommitResponse,
    "kv_batch_get": kp.BatchGetResponse,
    "kv_batch_rollback": kp.BatchRollbackResponse,
    "kv_cleanup": kp.CleanupResponse,
    "kv_pessimistic_lock": kp.PessimisticLockResponse,
    "kv_pessimistic_rollback": kp.PessimisticRollbackResponse,
    "kv_txn_heart_beat": kp.TxnHeartBeatResponse,
    "kv_check_txn_status": kp.CheckTxnStatusResponse,
    "kv_check_secondary_locks": kp.CheckSecondaryLocksResponse,
    "kv_scan_lock": kp.ScanLockResponse,
    "kv_resolve_lock": kp.ResolveLockResponse,
    "kv_delete_range": kp.DeleteRangeResponse,
    "raw_get": kp.RawGetResponse,
    "raw_put": kp.RawPutResponse,
    "raw_delete": kp.RawDeleteResponse,
    "raw_scan": kp.RawScanResponse,
    "raw_batch_get": kp.RawBatchGetResponse,
    "raw_batch_put": kp.RawBatchPutResponse,
    "raw_batch_delete": kp.RawBatchDeleteResponse,
    "raw_delete_range": kp.RawDeleteRangeResponse,
    "raw_compare_and_swap": kp.RawCasResponse,
    "raw_get_key_ttl": kp.RawGetKeyTtlResponse,
    "mvcc_get_by_key": kp.MvccGetByKeyResponse,
    "mvcc_get_by_start_ts": kp.MvccGetByStartTsResponse,
    "coprocessor": kp.CoprResponsePb,
    "deadlock_detect": kp.DeadlockResponse,
}


class PbClient:
    """Protobuf-mode client: kvproto messages over the framed transport.

    The reference analog is a TiDB/client-go peer speaking kvproto over gRPC
    (kv.rs service surface); here the same messages ride ``pb/<rpc>`` frames.
    """

    def __init__(self, host: str, port: int, security=None):
        from .server import Client

        self._client = Client(host, port, security=security)

    def call(self, method: str, req_msg, timeout: float = 30.0):
        raw = self._client.call(f"pb/{method}", req_msg.encode(), timeout=timeout)
        if isinstance(raw, dict):  # transport/gateway-level failure
            raise PbGatewayError(str(raw.get("error", raw)))
        return RESPONSE_TYPES[method].decode(raw)

    def close(self) -> None:
        self._client.close()


class PbGateway:
    """Decode kvproto request bytes, dispatch, encode kvproto response bytes."""

    def __init__(self, service):
        self.service = service

    def methods(self) -> list[str]:
        return sorted(HANDLERS)

    def handle(self, method: str, payload: bytes) -> bytes:
        entry = HANDLERS.get(method)
        if entry is None:
            raise PbGatewayError(f"no protobuf handler for {method!r}")
        req_cls, to_dict, fill = entry
        req = to_dict(req_cls.decode(payload))
        pb = req.pop("_pb", None)
        resp = self.service.dispatch(method, req)
        if method == "coprocessor":
            return _w_coprocessor(resp, pb).encode()
        if method == "deadlock_detect":
            return _w_deadlock(resp, pb).encode()
        return fill(resp).encode()
