"""Per-request resource attribution.

Re-expression of ``components/resource_metering`` (cpu/future_ext.rs tagging,
cpu/recorder sampling, reporter.rs top-N): requests tagged with a resource
group accumulate CPU time; a reporter surfaces the top consumers per window.
The reference samples /proc per-thread; here attribution wraps handler
execution with thread-CPU clocks — same accounting surface.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager


class ResourceTagFactory:
    """Accumulates CPU seconds and op counts per resource-group tag, and
    exposes which tag each OS thread is currently serving (the shared
    thread→tag registry the sampling recorder attributes against)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._cpu: dict[bytes, float] = {}
        self._ops: dict[bytes, int] = {}
        # native thread id -> tag currently attached on that thread
        self.current: dict[int, bytes] = {}

    @contextmanager
    def attach(self, tag: bytes):
        tid = threading.get_native_id()
        prev = self.current.get(tid)
        self.current[tid] = tag
        t0 = time.thread_time()
        try:
            yield
        finally:
            dt = time.thread_time() - t0
            if prev is None:
                self.current.pop(tid, None)
            else:
                self.current[tid] = prev
            with self._mu:
                self._cpu[tag] = self._cpu.get(tag, 0.0) + dt
                self._ops[tag] = self._ops.get(tag, 0) + 1

    def snapshot(self) -> dict[bytes, dict]:
        with self._mu:
            return {
                tag: {"cpu_secs": self._cpu[tag], "ops": self._ops.get(tag, 0)}
                for tag in self._cpu
            }

    def reset(self) -> dict[bytes, dict]:
        with self._mu:
            out = {
                tag: {"cpu_secs": self._cpu[tag], "ops": self._ops.get(tag, 0)}
                for tag in self._cpu
            }
            self._cpu.clear()
            self._ops.clear()
            return out


class ThreadCpuRecorder:
    """Per-thread CPU sampling from /proc (cpu/recorder/linux.rs): reads
    utime+stime of every thread in /proc/self/task/*/stat on an interval,
    attributes each delta to the tag the thread is CURRENTLY serving (via
    the factory's thread→tag registry) or to ``b""`` for untagged
    background work.  Unlike the attach() clocks this sees every thread in
    the process — pollers, compaction, appliers — whether or not a handler
    wrapped it."""

    UNTAGGED = b""

    def __init__(self, tags: ResourceTagFactory, interval: float = 1.0):
        self.tags = tags
        self.interval = interval
        self._clk = os.sysconf("SC_CLK_TCK")
        self._mu = threading.Lock()
        self._last: dict[int, float] = {}  # tid -> cumulative cpu secs seen
        self._by_tag: dict[bytes, float] = {}
        self._by_thread: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @staticmethod
    def _read_stat(tid: int) -> tuple[str, float] | None:
        try:
            with open(f"/proc/self/task/{tid}/stat", "rb") as f:
                raw = f.read()
        except OSError:
            return None
        # comm may contain spaces/parens: fields resume after the LAST ')'
        close = raw.rfind(b")")
        comm = raw[raw.find(b"(") + 1:close].decode(errors="replace")
        rest = raw[close + 2:].split()
        utime, stime = int(rest[11]), int(rest[12])
        return comm, utime + stime

    def sample(self) -> None:
        """One sampling pass (the recorder loop body; callable directly in
        tests)."""
        try:
            tids = [int(d) for d in os.listdir("/proc/self/task")]
        except OSError:
            return
        current = self.tags.current
        with self._mu:
            seen = set()
            for tid in tids:
                st = self._read_stat(tid)
                if st is None:
                    continue
                comm, ticks = st
                cpu = ticks / self._clk
                seen.add(tid)
                prev = self._last.get(tid)
                self._last[tid] = cpu
                if prev is None or cpu <= prev:
                    continue
                delta = cpu - prev
                tag = current.get(tid, self.UNTAGGED)
                self._by_tag[tag] = self._by_tag.get(tag, 0.0) + delta
                self._by_thread[comm] = self._by_thread.get(comm, 0.0) + delta
            for tid in list(self._last):
                if tid not in seen:  # thread exited
                    del self._last[tid]

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "by_tag": dict(self._by_tag),
                "by_thread": dict(self._by_thread),
            }

    def start(self) -> None:
        self.sample()  # baseline
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="res-cpu-recorder")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.sample()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)


class Reporter:
    """Windowed top-N reporting (reporter.rs): collect per interval, keep the
    heaviest groups, ship them to a receiver callback."""

    def __init__(self, tags: ResourceTagFactory, top_n: int = 10, interval: float = 1.0, receiver=None):
        self.tags = tags
        self.top_n = top_n
        self.interval = interval
        self.receiver = receiver or (lambda report: None)
        self.reports: deque[dict] = deque(maxlen=256)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.interval)
            self.tick()

    def tick(self) -> dict:
        window = self.tags.reset()
        top = dict(
            sorted(window.items(), key=lambda kv: kv[1]["cpu_secs"], reverse=True)[: self.top_n]
        )
        report = {"top": top, "groups": len(window)}
        self.reports.append(report)
        self.receiver(report)
        return report

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
