"""Per-request resource attribution.

Re-expression of ``components/resource_metering`` (cpu/future_ext.rs tagging,
cpu/recorder sampling, reporter.rs top-N): requests tagged with a resource
group accumulate CPU time; a reporter surfaces the top consumers per window.
The reference samples /proc per-thread; here attribution wraps handler
execution with thread-CPU clocks — same accounting surface.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager


class ResourceTagFactory:
    """Accumulates CPU seconds and op counts per resource-group tag."""

    def __init__(self):
        self._mu = threading.Lock()
        self._cpu: dict[bytes, float] = {}
        self._ops: dict[bytes, int] = {}

    @contextmanager
    def attach(self, tag: bytes):
        t0 = time.thread_time()
        try:
            yield
        finally:
            dt = time.thread_time() - t0
            with self._mu:
                self._cpu[tag] = self._cpu.get(tag, 0.0) + dt
                self._ops[tag] = self._ops.get(tag, 0) + 1

    def snapshot(self) -> dict[bytes, dict]:
        with self._mu:
            return {
                tag: {"cpu_secs": self._cpu[tag], "ops": self._ops.get(tag, 0)}
                for tag in self._cpu
            }

    def reset(self) -> dict[bytes, dict]:
        with self._mu:
            out = {
                tag: {"cpu_secs": self._cpu[tag], "ops": self._ops.get(tag, 0)}
                for tag in self._cpu
            }
            self._cpu.clear()
            self._ops.clear()
            return out


class Reporter:
    """Windowed top-N reporting (reporter.rs): collect per interval, keep the
    heaviest groups, ship them to a receiver callback."""

    def __init__(self, tags: ResourceTagFactory, top_n: int = 10, interval: float = 1.0, receiver=None):
        self.tags = tags
        self.top_n = top_n
        self.interval = interval
        self.receiver = receiver or (lambda report: None)
        self.reports: deque[dict] = deque(maxlen=256)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.interval)
            self.tick()

    def tick(self) -> dict:
        window = self.tags.reset()
        top = dict(
            sorted(window.items(), key=lambda kv: kv[1]["cpu_secs"], reverse=True)[: self.top_n]
        )
        report = {"top": top, "groups": len(window)}
        self.reports.append(report)
        self.receiver(report)
        return report

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
