"""MVCC garbage collection below the PD-driven safe point.

Re-expression of ``src/server/gc_worker`` (gc_worker.rs:687, gc_manager.rs,
compaction_filter.rs:156, applied_lock_collector.rs): versions no longer
visible at the safe point are dropped — the newest PUT at-or-below the safe
point survives as the read base, DELETEs at the tail become full removals,
LOCK/ROLLBACK markers below the safe point vanish (protected rollbacks only
once superseded).  The reference runs this inside RocksDB compaction; here it
is a range pass over CF_WRITE with the same retention rules, driven by the
auto-GC manager loop polling PD's safe point.
"""

from __future__ import annotations

import threading

from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE, WriteBatch
from ..storage.kv import Engine
from ..storage.txn_types import Key, Write, WriteType, append_ts, split_ts

from ..util import logger as slog

_LOG = slog.get_logger("gc_worker")


class GcWorker:
    def __init__(self, engine: Engine):
        self.engine = engine
        self.safe_point = 0
        self._mu = threading.Lock()

    # -- core GC pass -------------------------------------------------------

    def gc_range(self, start: bytes | None, end: bytes | None, safe_point: int, ctx: dict | None = None) -> dict:
        """One GC sweep over [start, end) at ``safe_point``. Returns stats."""
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_gcworker_gc_tasks_total", "GC sweeps run"
        ).inc(task="gc")
        with self._mu:
            if safe_point > self.safe_point:
                _LOG.info("gc safe point advanced", safe_point=safe_point)
            self.safe_point = max(self.safe_point, safe_point)
        snap = self.engine.snapshot(ctx)
        enc_start = Key.from_raw(start).encoded if start else b""
        enc_end = Key.from_raw(end).encoded if end else None
        wb = WriteBatch()
        stats = {"versions_deleted": 0, "keys_deleted": 0, "rollbacks_deleted": 0}

        cur_user: bytes | None = None
        base_found = False
        for wkey, wval in snap.scan_cf(CF_WRITE, enc_start, enc_end):
            user_key, commit_ts = split_ts(wkey)
            if user_key != cur_user:
                cur_user = user_key
                base_found = False
            write = Write.from_bytes(wval)
            if commit_ts > safe_point:
                continue  # still visible to readers at/below safe point
            if write.write_type in (WriteType.ROLLBACK, WriteType.LOCK):
                # markers below the safe point carry no data
                wb.delete_cf(CF_WRITE, wkey)
                stats["rollbacks_deleted"] += 1
                continue
            if not base_found:
                # the newest PUT/DELETE at-or-below safe point
                if write.write_type == WriteType.DELETE:
                    # a deleted tail: the tombstone itself can go
                    wb.delete_cf(CF_WRITE, wkey)
                    stats["keys_deleted"] += 1
                base_found = True
                continue
            # older than the base: drop version and its value
            wb.delete_cf(CF_WRITE, wkey)
            if write.short_value is None and write.write_type == WriteType.PUT:
                wb.delete_cf(CF_DEFAULT, append_ts(user_key, write.start_ts))
            stats["versions_deleted"] += 1
        if not wb.is_empty():
            self.engine.write(ctx, wb)
        return stats

    # -- green GC support (physical lock scan) ------------------------------

    def physical_scan_lock(self, max_ts: int, start: bytes | None = None, limit: int | None = None):
        """Scan CF_LOCK directly (bypassing leader reads) — applied_lock_collector."""
        from ..storage.txn_types import Lock

        snap = self.engine.snapshot(None)
        out = []
        enc_start = Key.from_raw(start).encoded if start else b""
        for k, v in snap.scan_cf(CF_LOCK, enc_start, None):
            lock = Lock.from_bytes(v)
            if lock.ts <= max_ts:
                out.append((Key.from_encoded(k).to_raw(), lock))
                if limit is not None and len(out) >= limit:
                    break
        return out

    def unsafe_destroy_range(self, start: bytes, end: bytes, ctx: dict | None = None) -> None:
        """Drop ALL versions and locks in [start, end) (gc_worker.rs
        unsafe_destroy_range:525 — used by drop-table).  Like the reference,
        this writes DIRECTLY to the local engine, bypassing raft: the range
        may span many regions and PD orders the call on every store."""
        enc_start = Key.from_raw(start).encoded
        enc_end = Key.from_raw(end).encoded
        store = getattr(self.engine, "store", None)
        wb = WriteBatch()
        if store is not None:
            from ..util import keys as keymod

            for cf in (CF_DEFAULT, CF_LOCK, CF_WRITE):
                wb.delete_range_cf(cf, keymod.data_key(enc_start), keymod.data_key(enc_end))
            store.engine.write(wb)
        else:
            for cf in (CF_DEFAULT, CF_LOCK, CF_WRITE):
                wb.delete_range_cf(cf, enc_start, enc_end)
            self.engine.write(ctx, wb)

    # -- applied lock collector (applied_lock_collector.rs) -----------------
    #
    # Green GC: instead of pausing writes to scan every store's CF_LOCK, PD
    # registers an observer at max_ts; stores collect locks they APPLY below
    # that ts while PD physical-scans existing locks.  check returns the
    # collected set + whether the collector stayed within bounds (clean).

    MAX_COLLECTED_LOCKS = 1024

    def register_lock_observer(self, max_ts: int) -> None:
        with self._mu:
            self._observer_max_ts = max_ts
            self._observer_locks: list[tuple[bytes, object]] = []
            self._observer_clean = True
        store = getattr(self.engine, "store", None)
        if store is not None and self._on_applied not in store.apply_observers:
            store.apply_observers.append(self._on_applied)

    def check_lock_observer(self) -> dict:
        from ..storage.txn_types import Key as TKey

        with self._mu:
            if getattr(self, "_observer_max_ts", None) is None:
                return {"error": {"other": "no lock observer registered"}}
            return {
                "is_clean": self._observer_clean,
                "locks": [
                    {
                        "key": TKey.from_encoded(k).to_raw(),
                        "lock_ts": lock.ts,
                        "primary": lock.primary,
                        "ttl": lock.ttl,
                    }
                    for k, lock in self._observer_locks
                ],
            }

    def remove_lock_observer(self) -> None:
        with self._mu:
            self._observer_max_ts = None
            self._observer_locks = []
        store = getattr(self.engine, "store", None)
        if store is not None and self._on_applied in store.apply_observers:
            store.apply_observers.remove(self._on_applied)

    def _on_applied(self, store, region, cmd) -> None:
        """Apply observer: collect CF_LOCK puts below the observer ts."""
        from ..storage.txn_types import Lock

        with self._mu:
            max_ts = getattr(self, "_observer_max_ts", None)
            if max_ts is None:
                return
            for op, cf, key, val in cmd.get("ops", ()):
                if cf != CF_LOCK or op != "put":
                    continue
                try:
                    lock = Lock.from_bytes(val)
                except Exception:  # noqa: BLE001 — foreign CF_LOCK payload
                    self._observer_clean = False
                    continue
                if lock.ts > max_ts:
                    continue
                if len(self._observer_locks) >= self.MAX_COLLECTED_LOCKS:
                    # bounded memory: the client falls back to physical scan
                    self._observer_clean = False
                    return
                self._observer_locks.append((key, lock))


class GcManager:
    """Auto-GC: polls PD's safe point and sweeps (gc_manager.rs:92,195)."""

    def __init__(self, gc_worker: GcWorker, pd, interval: float = 1.0):
        self.gc = gc_worker
        self.pd = pd
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_safe_point = 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                sp = self.pd.get_gc_safe_point()
                if sp > self.last_safe_point:
                    self.gc.gc_range(None, None, sp)
                    self.last_safe_point = sp
            except Exception:  # noqa: BLE001 — transient PD/leader errors must
                pass  # not kill auto-GC; next poll retries (gc_manager.rs)
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
