"""ServerCluster: N real stores wired over real TCP sockets.

Re-expression of ``components/test_raftstore``'s ``ServerCluster``
(src/server.rs:601): unlike the in-memory ``raft.cluster.Cluster`` (the
NodeCluster analog, which pumps messages deterministically through a
ChannelTransport), every node here runs its own background raft loop and all
peer traffic — raft batches AND chunked snapshots — rides the framed-TCP
transport through ``RaftClient``/``KvService.raft_*``.  Scenario tests
(failover, partition, snapshot catch-up, split/merge) therefore exercise the
actual networked stack.

Fault injection keeps the ``Filter`` API: filters attach to a node's
RemoteTransport (outbound), mirroring transport_simulate.rs.
"""

from __future__ import annotations

import itertools
import threading
import time

from ..pd.client import MockPd
from ..raft.raftkv import RaftKv
from ..raft.region import Peer as RegionPeer, Region, RegionEpoch
from ..raft.store import StorePeer
from ..storage.engine import CF_DEFAULT, WriteBatch
from ..util import keys as keymod, retry
from .node import Node
from .raft_client import RemoteTransport
from .server import Server
from .service import KvService

FIRST_REGION_ID = 1

# one policy for every leader-routed client loop in this harness (the
# reference client's backoff discipline): NotLeader/Epoch/Timeout re-route
# with exponential backoff + jitter; AssertionError/KeyError — the routing
# races that the old loops swallowed wholesale — ride the bounded "suspect"
# class and LOG on final failure instead of masking bugs silently
CLIENT_RETRY = retry.RetryPolicy(base_s=0.05, max_s=0.5, jitter=0.3)


class StoreNode:
    """One store: engine + Store + raft loops + TCP server (a TiKVServer).

    ``full_service`` additionally assembles the serving stack — RaftKv,
    Storage, a coprocessor endpoint, the resolved-ts sidecar (check_leader
    fan-out over the cluster's sockets), the read-degradation ladder
    (``read_plane``), and a WaiterManager whose detector forwards wait-for
    edges to the cluster's detector leader — so scenario tests can drive
    transactional RPCs AND follower/forwarded reads across real stores."""

    def __init__(self, cluster: "ServerCluster", store_id: int, engine=None,
                 full_service: bool = False):
        self.cluster = cluster
        self.full_service = full_service
        security = cluster.security
        self.transport = RemoteTransport(cluster.resolve, security=security)
        self.node = Node(cluster.pd, self.transport, store_id=store_id, engine=engine)
        self.store = self.node.store
        self.read_plane = None
        self.resolved_ts = None
        if full_service:
            from ..copr.endpoint import Endpoint
            from ..sidecar.resolved_ts import ResolvedTsEndpoint
            from .lock_manager import DetectorHandle, WaiterManager
            from .read_plane import ReadPlane
            from ..storage.storage import Storage

            self.read_plane = ReadPlane(
                store=self.store, resolver=cluster.resolve, security=security,
            )
            copr_kwargs = {"enable_device": False, **cluster.copr_kwargs}
            self.resolved_ts = ResolvedTsEndpoint(
                cluster.pd, store_id=store_id,
                # the fan-out rides the read plane's peer-client pool
                check_leader_send=lambda sid, payload: self.read_plane.call(
                    sid, "raft_check_leader", payload, timeout=2.0),
            )
            self.resolved_ts.attach_store(self.store)
            self.read_plane.resolved_ts = self.resolved_ts
            self.raftkv = RaftKv(self.store, resolved_ts=self.resolved_ts)
            self.lock_manager = WaiterManager(
                detector=DetectorHandle(self.store, cluster.resolve, security=security)
            )
            copr = Endpoint(self.raftkv, **copr_kwargs)
            if cluster.overload_config is not None:
                # overload control plane (docs/robustness.md "Overload"):
                # the standalone StoreServer wiring, mirrored so scenario
                # tests drive per-tenant admission over real sockets.  The
                # config object is SHARED across nodes on purpose — one
                # runtime toggle flips the whole cluster.
                from ..copr.overload import OverloadControl

                copr.overload = OverloadControl(
                    cluster.overload_config,
                    region_cache=copr.region_cache)
            self.service = KvService(
                Storage(engine=self.raftkv), raft_router=self.store,
                copr=copr,
                lock_manager=self.lock_manager, pd=cluster.pd,
                resolved_ts=self.resolved_ts, read_plane=self.read_plane,
            )
        else:
            self.lock_manager = None
            self.service = KvService(storage=None, raft_router=self.store)
        self.server = Server(self.service, security=security)
        self.running = False

    def start(self) -> None:
        self.server.start()
        self.cluster.addrs[self.store.store_id] = self.server.addr
        self.node.start(tick_interval=0.02, heartbeat_interval=0.2)
        if self.full_service and self.cluster.sched_continuous:
            # continuous coalescing lanes, the standalone default shape
            self.service.copr.scheduler.start()
        self.running = True

    def stop(self) -> None:
        self.running = False
        self.cluster.addrs.pop(self.store.store_id, None)
        if self.full_service:
            self.service.copr.scheduler.stop()
        self.node.stop()
        self.server.stop()
        self.transport.close()
        if self.read_plane is not None:
            self.read_plane.close()
        if self.lock_manager is not None:
            self.lock_manager.close()


class ServerCluster:
    def __init__(
        self,
        n_stores: int,
        pd: MockPd | None = None,
        engines: dict | None = None,
        security=None,
        full_service: bool = False,
        copr_kwargs: dict | None = None,
        overload_config=None,
        sched_continuous: bool = False,
    ):
        self.security = security
        # full_service endpoint assembly knobs: extra Endpoint kwargs (e.g.
        # enable_device / sched_config), an OverloadConfig for the per-node
        # OverloadControl, and whether to run the continuous scheduler
        # lanes — the standalone StoreServer shape for scenario tests
        self.copr_kwargs = copr_kwargs or {}
        self.overload_config = overload_config
        self.sched_continuous = sched_continuous
        self.pd = pd or MockPd()
        self.addrs: dict[int, tuple[str, int]] = {}
        self.nodes: dict[int, StoreNode] = {}
        self._ids = itertools.count(5000)
        self._engines = engines or {}
        # region -> leader store route cache, refreshed from NotLeader hints
        # (the client-go region-cache role): must_put/must_get consult it
        # before falling back to the wait_leader scan
        self._route: dict[int, int] = {}
        for sid in range(1, n_stores + 1):
            self.nodes[sid] = StoreNode(self, sid, engine=self._engines.get(sid),
                                        full_service=full_service)

    # -- addressing (resolve.rs: store id -> socket addr through PD) --------

    def resolve(self, store_id: int) -> tuple[str, int] | None:
        return self.addrs.get(store_id)

    def alloc_id(self) -> int:
        return self.pd.alloc_id()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for node in self.nodes.values():
            if not node.running:
                node.start()

    def bootstrap(self, store_ids: list[int] | None = None) -> Region:
        sids = store_ids or list(self.nodes)
        peers = [RegionPeer(self.alloc_id(), sid) for sid in sids]
        region = Region(FIRST_REGION_ID, b"", b"", RegionEpoch(), peers)
        self.pd.bootstrap_region(region.clone())
        for sid in sids:
            self.nodes[sid].store.create_peer(region)
        return region

    def run(self) -> None:
        """start + bootstrap + elect a first leader (Cluster::run)."""
        self.start()
        self.bootstrap()
        first = self.nodes[min(self.nodes)]
        first.store.peers[FIRST_REGION_ID].node.campaign()
        self.wait_leader(FIRST_REGION_ID)

    def shutdown(self) -> None:
        for node in self.nodes.values():
            if node.running:
                node.stop()

    def stop_node(self, store_id: int) -> None:
        self.nodes[store_id].stop()

    def restart_node(self, store_id: int) -> None:
        """Reboot a store over the SAME engine (state survives like a real
        restart over a durable engine; fsm/store.rs init recovers peers)."""
        old = self.nodes[store_id]
        assert not old.running, f"store {store_id} still running"
        node = StoreNode(self, store_id, engine=old.store.engine,
                         full_service=old.full_service)
        node.store.recover()
        self.nodes[store_id] = node
        node.start()

    # -- observation --------------------------------------------------------

    def leader_peer(self, region_id: int) -> StorePeer | None:
        leaders = []
        for node in self.nodes.values():
            if not node.running:
                continue
            p = node.store.peers.get(region_id)
            if p is not None and p.node.is_leader():
                leaders.append(p)
        if not leaders:
            return None
        return max(leaders, key=lambda p: p.node.term)

    def wait_leader(self, region_id: int, timeout: float = 10.0) -> StorePeer:
        return retry.wait_until(
            lambda: self.leader_peer(region_id), timeout,
            desc=f"leader for region {region_id}",
        )

    def wait_applied_on(self, store_id: int, region_id: int, index: int, timeout: float = 10.0) -> None:
        def applied():
            p = self.nodes[store_id].store.peers.get(region_id)
            return p is not None and p.node.applied >= index

        retry.wait_until(
            applied, timeout,
            desc=f"store {store_id} region {region_id} applied index {index}",
        )

    def get_on_store(self, store_id: int, key: bytes, cf: str = CF_DEFAULT) -> bytes | None:
        return self.nodes[store_id].store.engine.get_cf(cf, keymod.data_key(key))

    def wait_get_on_store(self, store_id: int, key: bytes, value: bytes, timeout: float = 10.0) -> None:
        retry.wait_until(
            lambda: self.get_on_store(store_id, key) == value, timeout,
            desc=f"store {store_id} sees {key!r}={value!r}",
        )

    # -- KV (leader-routed, with NotLeader retry like a real client) --------

    def region_for_key(self, key: bytes) -> int:
        for node in self.nodes.values():
            if not node.running:
                continue
            p = node.store.region_for_key(key)
            if p is not None:
                return p.region.id
        raise KeyError(key)

    def _routed_leader(self, region_id: int, timeout: float = 2.0) -> StorePeer:
        """Leader lookup through the route cache: a cached NotLeader hint
        answers without the all-store wait_leader poll; a stale entry drops
        and falls back."""
        sid = self._route.get(region_id)
        if sid is not None:
            node = self.nodes.get(sid)
            if node is not None and node.running:
                p = node.store.peers.get(region_id)
                if p is not None and p.node.is_leader():
                    return p
            self._route.pop(region_id, None)
        p = self.wait_leader(region_id, timeout=timeout)
        self._route[region_id] = p.store.store_id
        return p

    def _note_not_leader(self, region_id: int, exc: Exception) -> None:
        """NotLeader hints refresh the route cache instead of forcing the
        next attempt back through wait_leader's full poll."""
        from ..raft.region import NotLeaderError

        if isinstance(exc, NotLeaderError) and exc.leader_store:
            self._route[region_id] = exc.leader_store
        else:
            self._route.pop(region_id, None)

    def must_put(self, key: bytes, value: bytes, cf: str = CF_DEFAULT, timeout: float = 10.0) -> None:
        """Leader-routed put with the shared retry policy: NotLeader/Epoch/
        Timeout re-route freely; AssertionError/KeyError (routing races, but
        also how a REAL bug would surface) ride the bounded suspect class."""
        def attempt():
            region_id = self.region_for_key(key)
            leader = self._routed_leader(region_id)
            kv = RaftKv(leader.store)
            wb = WriteBatch()
            wb.put_cf(cf, key, value)
            try:
                kv.write({"region_id": region_id}, wb)
            except Exception as e:  # noqa: BLE001 — hint + re-raise to retry
                self._note_not_leader(region_id, e)
                raise

        retry.call(attempt, policy=CLIENT_RETRY, timeout=timeout,
                   site="server_cluster.must_put")

    def must_get(self, key: bytes, cf: str = CF_DEFAULT, timeout: float = 10.0,
                 stale_fallback: bool = False,
                 max_staleness: int | None = None) -> bytes | None:
        """Leader-routed snapshot read.  ``stale_fallback=True`` opts into
        the degraded mode (docs/stale_reads.md): when no leader is
        reachable within the budget, serve from any replica at the freshest
        RegionReadProgress watermark — bounded by ``max_staleness``
        timestamp units behind the current TSO (unbounded when None)."""
        def attempt():
            region_id = self.region_for_key(key)
            leader = self._routed_leader(region_id)
            kv = RaftKv(leader.store)
            try:
                snap = kv.snapshot({"region_id": region_id})
            except Exception as e:  # noqa: BLE001
                self._note_not_leader(region_id, e)
                raise
            return snap.get_cf(cf, key)

        try:
            return retry.call(attempt, policy=CLIENT_RETRY, timeout=timeout,
                              site="server_cluster.must_get")
        except Exception:
            if not stale_fallback:
                raise
            return self.stale_get(key, cf=cf, max_staleness=max_staleness)

    def stale_get(self, key: bytes, cf: str = CF_DEFAULT,
                  read_ts: int | None = None,
                  max_staleness: int | None = None) -> bytes | None:
        """Follower stale read: serve off ANY replica whose
        RegionReadProgress admits ``read_ts`` (default: the freshest
        watermark any live replica publishes).  ``max_staleness`` bounds
        how far behind the current TSO that watermark may be."""
        region_id = self.region_for_key(key)
        nodes = [n for n in self.nodes.values()
                 if n.running and n.resolved_ts is not None]
        if not nodes:
            raise RuntimeError("stale reads need full_service store nodes")
        if read_ts is None:
            read_ts = max(n.resolved_ts.progress_of(region_id)[0] for n in nodes)
        if max_staleness is not None:
            now = self.pd.get_tso()
            if now - read_ts > max_staleness:
                raise RaftKv.DataNotReadyError(region_id, now - max_staleness,
                                                read_ts)
        last: Exception | None = None
        for node in nodes:
            kv = RaftKv(node.store, resolved_ts=node.resolved_ts)
            try:
                snap = kv.snapshot({"region_id": region_id,
                                    "stale_read": True, "read_ts": read_ts})
                return snap.get_cf(cf, key)
            except Exception as e:  # noqa: BLE001 — next replica may serve
                last = e
        raise last if last is not None else KeyError(key)

    def coprocessor_rows(self, store_id: int, dag, ranges, start_ts: int,
                         chunk: bool = False, context: dict | None = None,
                         timeout: float = 30.0) -> list[list]:
        """Socket coprocessor call against one store with per-request
        TypeChunk opt-in (docs/wire_path.md "Columnar chunk responses"):
        ``chunk=True`` asks for column-slab responses (``encode_type`` +
        ``data_parts`` on the wire) and decodes them against the sent plan;
        the datum path stays the default.  Returns decoded rows either way
        — value-identical across encodings by the differential contract."""
        from dataclasses import replace

        from ..copr import dag as dag_mod
        from ..copr.dag_wire import dag_to_wire
        from .server import Client

        if chunk and dag.encode_type != dag_mod.ENC_TYPE_CHUNK:
            dag = replace(dag, encode_type=dag_mod.ENC_TYPE_CHUNK)
        addr = self.addrs[store_id]
        client = Client(*addr)
        try:
            r = client.call("coprocessor", {
                "dag": dag_to_wire(dag),
                "ranges": [list(rng) for rng in ranges],
                "start_ts": start_ts,
                "context": dict(context or {}),
            }, timeout=timeout)
        finally:
            client.close()
        if isinstance(r, dict) and r.get("error"):
            raise RuntimeError(f"coprocessor failed: {r['error']}")
        return dag_mod.decode_wire_response(r, dag).iter_rows()

    def set_device_owners(self, owners: dict[int, int]) -> None:
        """Push a device-owner placement map (region -> store) into every
        full-service node's read plane — the deterministic test-harness
        stand-in for the standalone deployment's PD heartbeat advertisement
        (docs/wire_path.md)."""
        for node in self.nodes.values():
            if node.read_plane is not None:
                node.read_plane.set_device_owners(owners)

    def advance_resolved_ts(self) -> dict[int, dict[int, int]]:
        """One watermark advance round on every full-service store (the
        standalone deployment's background loop, driven explicitly so tests
        stay deterministic)."""
        out: dict[int, dict[int, int]] = {}
        for node in self.nodes.values():
            if node.running and node.resolved_ts is not None:
                out[node.store.store_id] = node.resolved_ts.advance_all()
        return out

    # -- admin --------------------------------------------------------------

    def _run_admin(self, leader: StorePeer, cmd: dict, timeout: float = 10.0) -> None:
        done = threading.Event()
        res: list = []

        def cb(r):
            res.append(r)
            done.set()

        leader.propose_cmd(cmd, cb)
        if not done.wait(timeout):
            raise TimeoutError(f"admin command on region {leader.region.id} timed out")
        if isinstance(res[0], Exception):
            raise res[0]

    def ingest_sst(self, region_id: int, payload: bytes, timeout: float = 30.0) -> None:
        """Propose a raft ingest_sst admin command: the staged entries ride
        the log entry, so every replica (and any catching-up one) applies
        them (fsm/apply.rs exec_ingest_sst shape).  Retries leadership
        churn the way a real import client does (must_put discipline)."""
        def attempt():
            leader = self.wait_leader(region_id)
            cmd = {
                "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
                "admin": ("ingest_sst", payload),
            }
            try:
                self._run_admin(leader, cmd, timeout=2.0)
            except KeyError as e:
                # payload outside the region range: re-route the policy's
                # default suspect classification to permanent — retrying a
                # malformed import can never land it
                e.retry_class = "permanent"
                raise

        retry.call(attempt, policy=CLIENT_RETRY, timeout=timeout,
                   site="server_cluster.ingest_sst")

    def split_region(self, region_id: int, split_key: bytes) -> int:
        leader = self.wait_leader(region_id)
        new_region_id = self.alloc_id()
        new_pids = [self.alloc_id() for _ in leader.region.peers]
        done = threading.Event()
        res: list = []

        def cb(r):
            res.append(r)
            done.set()

        leader.propose_split(split_key, new_region_id, new_pids, cb)
        if not done.wait(10.0):
            raise TimeoutError("split timed out")
        if isinstance(res[0], Exception):
            raise res[0]
        self.wait_leader(new_region_id)
        return new_region_id

    def add_peer(self, region_id: int, store_id: int) -> int:
        leader = self.wait_leader(region_id)
        new_pid = self.alloc_id()
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "add", new_pid, store_id),
        }
        self._run_admin(leader, cmd)
        return new_pid

    def remove_peer(self, region_id: int, peer_id: int) -> None:
        leader = self.wait_leader(region_id)
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "remove", peer_id, 0),
        }
        self._run_admin(leader, cmd)

    def transfer_leader(self, region_id: int, to_store: int, timeout: float = 10.0) -> None:
        """Prefer the proper leader-side transfer (TIMEOUT_NOW once the
        target's log is caught up); fall back to target-side campaigns only
        at a slow cadence — a 0.1s campaign loop bumps terms faster than a
        loaded cluster can replicate, livelocking the very catch-up the
        election needs."""
        peer = self.nodes[to_store].store.peers[region_id]
        pacing = {"ordered_at": 0.0,   # last ACCEPTED leader-side order
                  "forced_at": 0.0}    # last target-side forced campaign

        def step() -> bool:
            if peer.node.is_leader():
                return True
            now = time.monotonic()
            cur = self.leader_peer(region_id)
            ordered = False
            if (cur is not None and cur.store.store_id != to_store
                    and now - pacing["ordered_at"] > 1.0):
                # leader-side order at most 1/s: TIMEOUT_NOW re-sent every
                # loop tick would force-campaign (and term-bump) the target
                # per delayed delivery, churning the very election it runs
                ordered = cur.transfer_leader_to(peer.peer_id)
                if ordered:
                    pacing["ordered_at"] = now
            if not ordered and now - max(pacing.values()) > 1.0:
                # the polite path is refused (learner target, or match never
                # equals last_index under a concurrent writer) or there is
                # no leader: fall back to the forced campaign — at a slow
                # cadence so replication can still outrun the term bumps
                peer.node.campaign()
                pacing["forced_at"] = now
            return False

        retry.wait_until(step, timeout, interval=0.05,
                         desc=f"store {to_store} takes region {region_id}")
