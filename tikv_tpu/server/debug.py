"""Debugger: offline/online store inspection.

Re-expression of ``src/server/debug.rs:120`` (``Debugger``: get/raft_log/
region_info/region_size/scan_mvcc/compact/bad_regions/recover) — the engine
backing ``tikv-ctl`` and the Debug gRPC service.
"""

from __future__ import annotations

from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_RAFT, CF_WRITE, KvEngine
from ..storage.txn_types import Key, Lock, Write, split_ts
from ..util import codec, keys


class Debugger:
    def __init__(self, engine: KvEngine, raft_log=None):
        self.engine = engine
        # the store's log engine (native/raftlog.py) when enabled: region
        # surgery must wipe entries + hard state there too, or recover()
        # would restore stale votes/entries beside freshly written meta.
        # (named *_engine: `raft_log` is already this class's inspection RPC)
        self.raft_log_engine = raft_log

    def _clean_raft_log(self, region_id: int) -> None:
        if self.raft_log_engine is not None:
            self.raft_log_engine.clean(region_id)

    def get(self, cf: str, raw_key: bytes) -> bytes | None:
        return self.engine.get_cf(cf, keys.data_key(raw_key))

    def region_info(self, region_id: int) -> dict | None:
        from ..raft.store import decode_region

        snap = self.engine.snapshot()
        state = snap.get_cf(CF_RAFT, keys.region_state_key(region_id))
        if state is None:
            return None
        region, _merging = decode_region(state)
        raft_state = snap.get_cf(CF_RAFT, keys.raft_state_key(region_id))
        apply_raw = snap.get_cf(CF_RAFT, keys.apply_state_key(region_id))
        info = {
            "region": {
                "id": region.id,
                "start_key": region.start_key.hex(),
                "end_key": region.end_key.hex(),
                "epoch": (region.epoch.conf_ver, region.epoch.version),
                "peers": [(p.peer_id, p.store_id) for p in region.peers],
            }
        }
        if raft_state is not None:
            info["raft_state"] = {
                "term": codec.decode_u64(raft_state, 0),
                "vote": codec.decode_u64(raft_state, 8),
                "commit": codec.decode_u64(raft_state, 16),
            }
        if apply_raw is not None:
            info["apply_state"] = {"applied_index": codec.decode_u64(apply_raw)}
        return info

    def all_regions(self) -> list[int]:
        snap = self.engine.snapshot()
        prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
        out = []
        for k, _ in snap.scan_cf(CF_RAFT, prefix, prefix[:-1] + bytes([prefix[-1] + 1])):
            out.append(codec.decode_u64(k, 2))
        return out

    def raft_log(self, region_id: int, index: int) -> dict | None:
        from ..raft.store import _decode_entry, decode_cmd

        raw = self.engine.get_cf(CF_RAFT, keys.raft_log_key(region_id, index))
        if raw is None:
            return None
        e = _decode_entry(raw)
        out = {"term": e.term, "index": e.index, "conf_change": e.conf_change}
        if e.data:
            try:
                out["cmd"] = decode_cmd(e.data)
            except (ValueError, KeyError, IndexError):
                out["data"] = e.data.hex()
        return out

    def region_size(self, region_id: int) -> dict | None:
        from ..raft.store import decode_region

        state = self.engine.get_cf(CF_RAFT, keys.region_state_key(region_id))
        if state is None:
            return None
        region, _merging = decode_region(state)
        snap = self.engine.snapshot()
        start = keys.data_key(region.start_key)
        end = keys.data_end_key(region.end_key)
        out = {}
        for cf in (CF_DEFAULT, CF_LOCK, CF_WRITE):
            n = size = 0
            for k, v in snap.scan_cf(cf, start, end):
                n += 1
                size += len(k) + len(v)
            out[cf] = {"keys": n, "bytes": size}
        return out

    def scan_mvcc(self, start: bytes | None = None, end: bytes | None = None, limit: int = 100) -> list[dict]:
        """Every version of every key in range — the recover-mvcc view."""
        snap = self.engine.snapshot()
        enc_start = keys.data_key(Key.from_raw(start).encoded) if start else keys.DATA_MIN_KEY
        enc_end = keys.data_key(Key.from_raw(end).encoded) if end else keys.DATA_MAX_KEY
        out: list[dict] = []
        for k, v in snap.scan_cf(CF_WRITE, enc_start, enc_end, limit=limit):
            user_enc, commit_ts = split_ts(keys.origin_key(k))
            w = Write.from_bytes(v)
            out.append(
                {
                    "key": Key.from_encoded(user_enc).to_raw().hex(),
                    "commit_ts": commit_ts,
                    "start_ts": w.start_ts,
                    "type": w.write_type.name,
                    "short_value": w.short_value.hex() if w.short_value else None,
                }
            )
        return out

    def scan_locks(self, limit: int = 100) -> list[dict]:
        snap = self.engine.snapshot()
        out = []
        for k, v in snap.scan_cf(CF_LOCK, keys.DATA_MIN_KEY, keys.DATA_MAX_KEY, limit=limit):
            lock = Lock.from_bytes(v)
            out.append(
                {
                    "key": Key.from_encoded(keys.origin_key(k)).to_raw().hex(),
                    "ts": lock.ts,
                    "type": lock.lock_type.name,
                    "primary": lock.primary.hex(),
                    "ttl": lock.ttl,
                }
            )
        return out

    def region_properties(self, region_id: int) -> dict | None:
        """MVCC + size properties for a region (debug.rs region_properties:
        mvcc.num_rows/num_puts/num_deletes, min/max commit ts, middle key for
        approximate splits)."""
        from ..raft.store import decode_region

        state = self.engine.get_cf(CF_RAFT, keys.region_state_key(region_id))
        if state is None:
            return None
        region, _merging = decode_region(state)
        snap = self.engine.snapshot()  # ONE snapshot: mvcc and size agree
        start = keys.data_key(region.start_key)
        end = keys.data_end_key(region.end_key)
        num_puts = num_deletes = num_versions = num_rows = 0
        min_ts = max_ts = None
        last_user = None
        sizes = {}
        wn = wsize = 0
        for k, v in snap.scan_cf(CF_WRITE, start, end):
            wn += 1
            wsize += len(k) + len(v)
            user, commit_ts = split_ts(keys.origin_key(k))
            w = Write.from_bytes(v)
            if w.write_type.name == "PUT":
                num_puts += 1
            elif w.write_type.name == "DELETE":
                num_deletes += 1
            num_versions += 1
            if user != last_user:  # rows = distinct user keys
                num_rows += 1
                last_user = user
            min_ts = commit_ts if min_ts is None else min(min_ts, commit_ts)
            max_ts = commit_ts if max_ts is None else max(max_ts, commit_ts)
        sizes[CF_WRITE] = {"keys": wn, "bytes": wsize}
        for cf in (CF_DEFAULT, CF_LOCK):
            n = size = 0
            for k, v in snap.scan_cf(cf, start, end):
                n += 1
                size += len(k) + len(v)
            sizes[cf] = {"keys": n, "bytes": size}
        middle = None
        if wn:
            # second bounded pass over the same snapshot instead of holding
            # every key: O(1) memory for a debug RPC on a big region
            for i, (k, _v) in enumerate(snap.scan_cf(CF_WRITE, start, end)):
                if i == wn // 2:
                    middle = Key.from_encoded(split_ts(keys.origin_key(k))[0]).to_raw().hex()
                    break
        return {
            "mvcc": {
                "num_rows": num_rows,
                "num_versions": num_versions,
                "num_puts": num_puts,
                "num_deletes": num_deletes,
                "num_locks": sizes[CF_LOCK]["keys"],
                "min_commit_ts": min_ts,
                "max_commit_ts": max_ts,
            },
            "size": sizes,
            "middle_key": middle,
        }

    def unsafe_recover(self, failed_stores: set[int]) -> list[int]:
        """Force-remove peers on permanently failed stores from every
        persisted region state so the survivors can form a quorum again
        (debug.rs remove_failed_stores / tikv-ctl unsafe-recover
        remove-fail-stores).  MUST run with the store process stopped — it
        rewrites region metadata AND the ConfState embedded in the raft-state
        blob (voters/learners/outgoing), then the next recover() comes up
        with the shrunken membership.  Returns the modified region ids."""
        from ..raft.store import decode_region, encode_region

        snap = self.engine.snapshot()
        prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
        modified = []
        for k, v in snap.scan_cf(CF_RAFT, prefix, prefix[:-1] + bytes([prefix[-1] + 1])):
            rid = codec.decode_u64(k, 2)
            region, merging = decode_region(v)
            dead = [p for p in region.peers if p.store_id in failed_stores]
            if not dead:
                continue
            dead_ids = {p.peer_id for p in dead}
            region.peers = [p for p in region.peers if p.peer_id not in dead_ids]
            region.epoch.conf_ver += len(dead_ids)
            self.engine.put_cf(CF_RAFT, keys.region_state_key(rid), encode_region(region, merging))
            state = self.engine.get_cf(CF_RAFT, keys.raft_state_key(rid))
            if state is not None and len(state) > 40:
                # rewrite the persisted ConfState minus the dead peers
                from ..raft.store import decode_conf_state, encode_conf_state

                voters, learners, outgoing, witnesses = decode_conf_state(state)
                self.engine.put_cf(
                    CF_RAFT,
                    keys.raft_state_key(rid),
                    state[:40]
                    + encode_conf_state(
                        voters - dead_ids, learners - dead_ids,
                        outgoing - dead_ids, witnesses - dead_ids,
                    ),
                )
            modified.append(rid)
        return modified

    def bad_regions(self) -> list[tuple[int, str]]:
        """Regions whose persisted state fails sanity checks (debug.rs bad_regions)."""
        from ..raft.store import decode_region

        bad = []
        snap = self.engine.snapshot()
        prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
        for k, v in snap.scan_cf(CF_RAFT, prefix, prefix[:-1] + bytes([prefix[-1] + 1])):
            rid = codec.decode_u64(k, 2)
            try:
                region, _merging = decode_region(v)
            except (ValueError, IndexError) as e:
                bad.append((rid, f"corrupt region state: {e}"))
                continue
            if region.end_key and region.start_key >= region.end_key:
                bad.append((rid, "empty key range"))
            if not region.peers:
                bad.append((rid, "no peers"))
            raft_state = snap.get_cf(CF_RAFT, keys.raft_state_key(rid))
            apply_raw = snap.get_cf(CF_RAFT, keys.apply_state_key(rid))
            if raft_state is not None and apply_raw is not None:
                commit = codec.decode_u64(raft_state, 16)
                applied = codec.decode_u64(apply_raw)
                if applied > commit:
                    bad.append((rid, f"applied {applied} > commit {commit}"))
        return bad

    def compact(self, cf: str | None = None) -> dict:
        """Trigger engine compaction (debug.rs compact / tikv-ctl compact):
        native engines fold memtable garbage and merge sorted runs; engines
        without a compaction surface report so instead of failing."""
        all_cfs = ("default", "lock", "write", "raft")
        if cf is not None and cf not in all_cfs:
            raise ValueError(f"unknown cf {cf!r} (expected one of {all_cfs})")
        dropped = 0
        merged = 0
        eng = self.engine
        if cf is not None and hasattr(eng, "compact_cf"):
            dropped = eng.compact_cf(cf)
        elif hasattr(eng, "compact"):
            dropped = eng.compact()
        if hasattr(eng, "merge_runs"):
            for c in [cf] if cf else list(all_cfs):
                try:
                    merged += eng.merge_runs(c)
                except RuntimeError:
                    pass  # engine-level merge failure; count stays honest
        return {"dropped_versions": dropped, "merged_runs": merged,
                "supported": hasattr(eng, "compact")}

    def tombstone_region(self, region_id: int) -> bool:
        """Erase a region's persisted identity on THIS store (tikv-ctl
        tombstone): a wrecked replica must not resurrect at next boot.
        Offline-only — run with the store process stopped."""
        snap = self.engine.snapshot()
        if snap.get_cf(CF_RAFT, keys.region_state_key(region_id)) is None:
            return False
        from ..raft.store import erase_region_state

        erase_region_state(self.engine, region_id)
        self._clean_raft_log(region_id)
        return True

    def recreate_region(self, region_id: int, start: bytes, end: bytes,
                        store_id: int, peer_id: int) -> None:
        """Write a fresh single-peer region state (tikv-ctl recreate-region):
        disaster recovery when every replica of a range is gone — the new
        empty region serves the key range again.  Offline-only."""
        from ..raft.region import Peer, Region, RegionEpoch
        from ..raft.store import encode_region, erase_region_state

        # wipe stale raft state / apply state / log first: recover() would
        # otherwise restore the OLD ConfState (dead voters) and old entries
        # alongside the new region — an unelectable peer and replayed garbage
        erase_region_state(self.engine, region_id)
        self._clean_raft_log(region_id)
        region = Region(region_id, start, end, RegionEpoch(1, 1),
                        [Peer(peer_id, store_id)])
        self.engine.put_cf(CF_RAFT, keys.region_state_key(region_id),
                           encode_region(region, False))

    def recover_mvcc(self, dry_run: bool = True, safe_ts: int = 0) -> dict:
        """Cross-CF MVCC consistency repair (debug.rs MvccChecker /
        tikv-ctl recover-mvcc):

        * orphan locks with start_ts below ``safe_ts`` (their txn can no
          longer commit) — removed.  ``safe_ts`` defaults to 0 — i.e. remove
          NOTHING until the operator supplies the GC safe point: a
          destructive filter must not default to "everything"
        * dangling CF_DEFAULT values referenced by neither a CF_WRITE record
          nor a live CF_LOCK entry (an uncommitted prewrite's value is NOT
          dangling) — removed
        With ``dry_run`` the report is produced and nothing is written."""
        from ..storage.engine import WriteBatch

        snap = self.engine.snapshot()
        wb = WriteBatch()
        orphan_locks: list[bytes] = []
        dangling_defaults: list[bytes] = []
        for lk, lv in snap.scan_cf(CF_LOCK, keys.DATA_PREFIX, keys.DATA_MAX_KEY):
            lock = Lock.from_bytes(lv)
            if lock.ts < safe_ts:
                orphan_locks.append(lk)
                wb.delete_cf(CF_LOCK, lk)
        # every CF_DEFAULT entry must be referenced by a CF_WRITE record (or
        # a surviving lock) whose start_ts matches the default key's suffix.
        # One reference-set pass per user key, not per version: a hot key
        # with V versions costs O(V), not O(V^2).
        orphaned = set(orphan_locks)
        cur_user: bytes | None = None
        refs: set[int] = set()
        for dk, _dv in snap.scan_cf(CF_DEFAULT, keys.DATA_PREFIX, keys.DATA_MAX_KEY):
            user, start_ts = split_ts(dk)
            if user != cur_user:
                cur_user = user
                refs = set()
                # NB: the ts suffix is DESC-encoded (leading 0xff bytes), so
                # a `user + 0xff` bound would exclude the user's own versions
                # — scan open-ended and stop at the first different user key
                for wk, wv in snap.scan_cf(CF_WRITE, user, keys.DATA_MAX_KEY):
                    wuser, _commit = split_ts(wk)
                    if wuser != user:
                        break
                    refs.add(Write.from_bytes(wv).start_ts)
                lv = snap.get_cf(CF_LOCK, user)
                if lv is not None and user not in orphaned:
                    refs.add(Lock.from_bytes(lv).ts)
            if start_ts not in refs:
                dangling_defaults.append(dk)
                wb.delete_cf(CF_DEFAULT, dk)
        if not dry_run and (orphan_locks or dangling_defaults):
            self.engine.write(wb)
        return {
            "orphan_locks": len(orphan_locks),
            "dangling_defaults": len(dangling_defaults),
            "applied": not dry_run,
        }
