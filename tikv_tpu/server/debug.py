"""Debugger: offline/online store inspection.

Re-expression of ``src/server/debug.rs:120`` (``Debugger``: get/raft_log/
region_info/region_size/scan_mvcc/compact/bad_regions/recover) — the engine
backing ``tikv-ctl`` and the Debug gRPC service.
"""

from __future__ import annotations

from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_RAFT, CF_WRITE, KvEngine
from ..storage.txn_types import Key, Lock, Write, split_ts
from ..util import codec, keys


class Debugger:
    def __init__(self, engine: KvEngine):
        self.engine = engine

    def get(self, cf: str, raw_key: bytes) -> bytes | None:
        return self.engine.get_cf(cf, keys.data_key(raw_key))

    def region_info(self, region_id: int) -> dict | None:
        from ..raft.store import decode_region

        snap = self.engine.snapshot()
        state = snap.get_cf(CF_RAFT, keys.region_state_key(region_id))
        if state is None:
            return None
        region, _merging = decode_region(state)
        raft_state = snap.get_cf(CF_RAFT, keys.raft_state_key(region_id))
        apply_raw = snap.get_cf(CF_RAFT, keys.apply_state_key(region_id))
        info = {
            "region": {
                "id": region.id,
                "start_key": region.start_key.hex(),
                "end_key": region.end_key.hex(),
                "epoch": (region.epoch.conf_ver, region.epoch.version),
                "peers": [(p.peer_id, p.store_id) for p in region.peers],
            }
        }
        if raft_state is not None:
            info["raft_state"] = {
                "term": codec.decode_u64(raft_state, 0),
                "vote": codec.decode_u64(raft_state, 8),
                "commit": codec.decode_u64(raft_state, 16),
            }
        if apply_raw is not None:
            info["apply_state"] = {"applied_index": codec.decode_u64(apply_raw)}
        return info

    def all_regions(self) -> list[int]:
        snap = self.engine.snapshot()
        prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
        out = []
        for k, _ in snap.scan_cf(CF_RAFT, prefix, prefix[:-1] + bytes([prefix[-1] + 1])):
            out.append(codec.decode_u64(k, 2))
        return out

    def raft_log(self, region_id: int, index: int) -> dict | None:
        from ..raft.store import _decode_entry, decode_cmd

        raw = self.engine.get_cf(CF_RAFT, keys.raft_log_key(region_id, index))
        if raw is None:
            return None
        e = _decode_entry(raw)
        out = {"term": e.term, "index": e.index, "conf_change": e.conf_change}
        if e.data:
            try:
                out["cmd"] = decode_cmd(e.data)
            except (ValueError, KeyError, IndexError):
                out["data"] = e.data.hex()
        return out

    def region_size(self, region_id: int) -> dict | None:
        from ..raft.store import decode_region

        state = self.engine.get_cf(CF_RAFT, keys.region_state_key(region_id))
        if state is None:
            return None
        region, _merging = decode_region(state)
        snap = self.engine.snapshot()
        start = keys.data_key(region.start_key)
        end = keys.data_end_key(region.end_key)
        out = {}
        for cf in (CF_DEFAULT, CF_LOCK, CF_WRITE):
            n = size = 0
            for k, v in snap.scan_cf(cf, start, end):
                n += 1
                size += len(k) + len(v)
            out[cf] = {"keys": n, "bytes": size}
        return out

    def scan_mvcc(self, start: bytes | None = None, end: bytes | None = None, limit: int = 100) -> list[dict]:
        """Every version of every key in range — the recover-mvcc view."""
        snap = self.engine.snapshot()
        enc_start = keys.data_key(Key.from_raw(start).encoded) if start else keys.DATA_MIN_KEY
        enc_end = keys.data_key(Key.from_raw(end).encoded) if end else keys.DATA_MAX_KEY
        out: list[dict] = []
        for k, v in snap.scan_cf(CF_WRITE, enc_start, enc_end, limit=limit):
            user_enc, commit_ts = split_ts(keys.origin_key(k))
            w = Write.from_bytes(v)
            out.append(
                {
                    "key": Key.from_encoded(user_enc).to_raw().hex(),
                    "commit_ts": commit_ts,
                    "start_ts": w.start_ts,
                    "type": w.write_type.name,
                    "short_value": w.short_value.hex() if w.short_value else None,
                }
            )
        return out

    def scan_locks(self, limit: int = 100) -> list[dict]:
        snap = self.engine.snapshot()
        out = []
        for k, v in snap.scan_cf(CF_LOCK, keys.DATA_MIN_KEY, keys.DATA_MAX_KEY, limit=limit):
            lock = Lock.from_bytes(v)
            out.append(
                {
                    "key": Key.from_encoded(keys.origin_key(k)).to_raw().hex(),
                    "ts": lock.ts,
                    "type": lock.lock_type.name,
                    "primary": lock.primary.hex(),
                    "ttl": lock.ttl,
                }
            )
        return out

    def bad_regions(self) -> list[tuple[int, str]]:
        """Regions whose persisted state fails sanity checks (debug.rs bad_regions)."""
        from ..raft.store import decode_region

        bad = []
        snap = self.engine.snapshot()
        prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
        for k, v in snap.scan_cf(CF_RAFT, prefix, prefix[:-1] + bytes([prefix[-1] + 1])):
            rid = codec.decode_u64(k, 2)
            try:
                region, _merging = decode_region(v)
            except (ValueError, IndexError) as e:
                bad.append((rid, f"corrupt region state: {e}"))
                continue
            if region.end_key and region.start_key >= region.end_key:
                bad.append((rid, "empty key range"))
            if not region.peers:
                bad.append((rid, "no peers"))
            raft_state = snap.get_cf(CF_RAFT, keys.raft_state_key(rid))
            apply_raw = snap.get_cf(CF_RAFT, keys.apply_state_key(rid))
            if raft_state is not None and apply_raw is not None:
                commit = codec.decode_u64(raft_state, 16)
                applied = codec.decode_u64(apply_raw)
                if applied > commit:
                    bad.append((rid, f"applied {applied} > commit {commit}"))
        return bad
