"""Read-degradation ladder: leader → one-hop forward → follower stale read
→ typed refusal with hints.

Re-expression of the reference's read routing resilience (raftstore's
forwarding of reads to the leader plus the stale-read path gated by
RegionReadProgress — see docs/stale_reads.md for the safety argument): a
store receiving a read for a region it does not lead should not just bounce
a ``NotLeader`` back across the WAN.  Instead:

1. **forward** the request ONE hop to the store it believes leads the
   region.  The hop is loop-guarded by a ``forwarded`` context flag — a
   forwarded request is never forwarded again, so two stores with stale
   views of each other can never ping-pong a request between them.
2. when the leader is **unreachable** (no route, connection error, timeout,
   or the per-store forward breaker is open), serve locally as a follower
   **stale read** iff the request permits it: the context carries
   ``stale_read``/``stale_fallback`` and a ``read_ts`` at or below the
   region's RegionReadProgress watermark (the engine enforces the pair:
   ``read_ts <= resolved_ts`` AND ``apply_index >= required_apply_index``).
3. else return the typed **refusal**: the ``not_leader`` /
   ``data_not_ready`` error enriched with the freshest leader hint, the
   store's ``safe_ts`` and the region's progress pair — so the client can
   re-route, lower its read ts, or back off watermark-aware
   (``util.retry``'s ``data_not_ready`` class).

Every rung is counted per outcome (``tikv_read_forward_total``,
``tikv_read_stale_serve_total``, ``tikv_read_refuse_total``) and charted on
the raft dashboard next to the ``tikv_resolved_ts_safe_ts_lag`` gauge.
"""

from __future__ import annotations

import time

from ..analysis.sanitizer import make_lock
from ..util import trace
from ..util.metrics import REGISTRY

#: per-store forward breaker: first-failure cooldown and the exponential
#: ceiling — a dead leader store costs one probe per cooldown, not one per
#: read that lands here
_BREAKER_BASE_S = 0.05
_BREAKER_MAX_S = 2.0


def _count_forward(outcome: str) -> None:
    REGISTRY.counter(
        "tikv_read_forward_total",
        "One-hop read forwards attempted by the dispatch tier, by outcome",
    ).inc(outcome=outcome)


def _count_stale_serve(path: str, cause: str) -> None:
    REGISTRY.counter(
        "tikv_read_stale_serve_total",
        "Reads served locally as follower stale reads by the dispatch "
        "tier, by request family and degradation cause",
    ).inc(path=path, cause=cause)


def _count_refuse(cause: str) -> None:
    REGISTRY.counter(
        "tikv_read_refuse_total",
        "Reads the dispatch tier refused with a typed hint-carrying error, "
        "by cause",
    ).inc(cause=cause)


def _count_owner_forward(outcome: str) -> None:
    REGISTRY.counter(
        "tikv_copr_owner_forward_total",
        "Device-eligible DAGs forwarded to the store owning the warm "
        "region image, by outcome",
    ).inc(outcome=outcome)


def _path_of(method: str) -> str:
    return "copr" if method.startswith("coprocessor") else "kv"


class ReadPlane:
    """One store's read dispatch tier.

    ``store`` (raft ``Store``) answers leadership lookups; ``resolved_ts``
    (``ResolvedTsEndpoint``) provides the ``safe_ts``/progress hints;
    ``resolver`` maps a store id to a socket address for the forward hop.
    ``send`` overrides the wire transport entirely — tests inject a
    callable ``(store_id, method, req, timeout) -> dict`` and never open a
    socket."""

    def __init__(self, store=None, resolved_ts=None, resolver=None,
                 security=None, send=None, forward_timeout: float = 2.0):
        self.store = store
        self.store_id = getattr(store, "store_id", None)
        self.resolved_ts = resolved_ts
        self.resolver = resolver
        self.security = security
        self.forward_timeout = forward_timeout
        self._send = send
        self._mu = make_lock("server.read_plane")
        self._clients: dict[int, object] = {}
        # per-store forward breaker: (consecutive failures, down-until)
        self._down: dict[int, tuple[int, float]] = {}
        # region -> device-owner store (docs/wire_path.md): the cluster map
        # refreshed from PD each heartbeat (advertise_device_regions); a
        # store receiving a device-eligible DAG whose warm image lives on
        # another store forwards it there instead of serving cold locally
        self._device_owners: dict[int, int] = {}

    # -- device-owner placement ----------------------------------------------

    def set_device_owners(self, owners: dict) -> None:
        with self._mu:
            self._device_owners = dict(owners)

    def device_owner_of(self, region_id) -> int | None:
        with self._mu:
            return self._device_owners.get(region_id)

    def device_owners(self) -> dict:
        with self._mu:
            return dict(self._device_owners)

    def forward_device_owner(self, method: str, req: dict, owner: int):
        """ONE hop to the device-owner store (loop-guarded by the same
        ``forwarded`` flag as the leader hop, sharing the per-store forward
        breaker).  The hop context adds ``stale_fallback`` so an owner that
        does not LEAD the region can still serve off its warm image through
        the follower stale rung.  Returns the owner's answer, or None when
        the caller should serve locally (hop failed, or the owner itself
        returned a region error — its serving is no better than ours)."""
        if not self._allow(owner):
            _count_owner_forward("breaker_open")
            return None
        fctx = dict(req.get("context") or {})
        fctx["forwarded"] = True
        fctx.setdefault("stale_fallback", True)
        freq = dict(req)
        freq["context"] = fctx
        with trace.span("ladder.owner_forward", target_store=owner,
                        store=self.store_id or "") as sp:
            # propagate the trace across the hop: the owner's RPC span
            # parents onto THIS forward span (the current span here)
            trace.inject(fctx)
            try:
                r = self.call(owner, method, freq)
            except TimeoutError:
                self._record_failure(owner)
                _count_owner_forward("timeout")
                sp.tag(outcome="timeout")
                return None
            except Exception:  # noqa: BLE001 — no route / conn refused / reset
                self._record_failure(owner)
                _count_owner_forward("error")
                sp.tag(outcome="error")
                return None
            self._record_success(owner)
            err = r.get("error") if isinstance(r, dict) else None
            if isinstance(err, dict):
                # the owner refused (NotLeader chain exhausted, watermark lag,
                # busy): local CPU serving still yields correct bytes
                _count_owner_forward("remote_region_error")
                sp.tag(outcome="remote_region_error")
                return None
            _count_owner_forward("ok")
            sp.tag(outcome="ok")
            return r

    # -- transport ----------------------------------------------------------

    def call(self, store_id: int, method: str, req: dict,
             timeout: float | None = None):
        """One RPC to a peer store (shared by the forward hop and the
        resolved-ts check_leader fan-out).  Raises on transport failure."""
        if self._send is not None:
            return self._send(store_id, method, req,
                              timeout or self.forward_timeout)
        c = self._client(store_id)
        if c is None:
            raise ConnectionError(f"no route to store {store_id}")
        try:
            return c.call(method, req, timeout=timeout or self.forward_timeout)
        except (ConnectionError, OSError, TimeoutError):
            self._drop_client(store_id, c)
            raise

    def _client(self, store_id: int):
        with self._mu:
            c = self._clients.get(store_id)
        if c is not None:
            return c
        if self.resolver is None:
            return None
        addr = self.resolver(store_id)
        if addr is None:
            return None
        from .server import Client

        # connect OUTSIDE the pool lock: a slow peer handshake must not
        # stall forwards to healthy stores.  A racing connect wastes one
        # socket; the loser closes.
        c = Client(addr[0], addr[1], security=self.security)
        with self._mu:
            cur = self._clients.setdefault(store_id, c)
        if cur is not c:
            try:
                c.close()
            except OSError:
                pass
        return cur

    def _drop_client(self, store_id: int, c) -> None:
        with self._mu:
            if self._clients.get(store_id) is c:
                self._clients.pop(store_id, None)
        try:
            c.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._mu:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except OSError:
                pass

    # -- per-store forward breaker ------------------------------------------

    def _allow(self, store_id: int) -> bool:
        now = time.monotonic()
        with self._mu:
            failures, until = self._down.get(store_id, (0, 0.0))
            if failures == 0:
                return True
            if now < until:
                return False
            # half-open: exactly ONE caller probes per cooldown lapse —
            # re-arm before releasing the lock so every concurrent read
            # keeps degrading instead of all piling onto a still-dead
            # store at once; the probe's outcome then clears or extends
            self._down[store_id] = (failures, now + self.forward_timeout)
            return True

    def _record_failure(self, store_id: int) -> None:
        now = time.monotonic()
        with self._mu:
            failures, _ = self._down.get(store_id, (0, 0.0))
            failures += 1
            cooldown = min(_BREAKER_BASE_S * (2.0 ** (failures - 1)),
                           _BREAKER_MAX_S)
            self._down[store_id] = (failures, now + cooldown)

    def _record_success(self, store_id: int) -> None:
        with self._mu:
            self._down.pop(store_id, None)

    # -- the ladder ---------------------------------------------------------

    def degrade(self, service, method: str, req: dict, resp: dict, local):
        """Run a locally-failed read down the ladder.  ``resp`` is the local
        serve's region-error response; ``local`` re-serves the request
        in-process (the stale rung)."""
        err = resp.get("error") or {}
        if "not_leader" in err:
            return self._on_not_leader(method, req, resp, local)
        if "data_not_ready" in err:
            return self._on_data_not_ready(method, req, resp, local)
        return resp

    def _on_not_leader(self, method: str, req: dict, resp: dict, local):
        ctx = req.get("context") or {}
        nl = resp["error"]["not_leader"]
        region_id = nl.get("region_id") or ctx.get("region_id")
        if ctx.get("forwarded"):
            # the loop guard: a forwarded request NEVER forwards again —
            # whatever this store can serve locally is the end of its ladder
            _count_forward("loop_guard")
            return self._stale_fallback(method, req, resp, local, region_id,
                                        cause="forwarded_not_leader")
        served, resp = self._forward_rung(method, req, resp, region_id,
                                          leader=nl.get("leader_store"))
        if served is not None:
            return served
        return self._stale_fallback(method, req, resp, local, region_id,
                                    cause="leader_unreachable")

    def _on_data_not_ready(self, method: str, req: dict, resp: dict, local):
        """A local stale read refused: this replica's watermark (or apply
        index) lags the requested read_ts.  The leader's RegionReadProgress
        is always current, so one forwarded hop can serve what we cannot —
        else the refusal carries ``resolved`` + ``safe_ts`` and the client
        backs off watermark-aware."""
        ctx = req.get("context") or {}
        dnr = resp["error"]["data_not_ready"]
        region_id = dnr.get("region_id") or ctx.get("region_id")
        if ctx.get("forwarded"):
            _count_forward("loop_guard")
        else:
            served, resp = self._forward_rung(method, req, resp, region_id)
            if served is not None:
                return served
        return self._refuse(resp, region_id, "data_not_ready")

    def _forward_rung(self, method: str, req: dict, resp: dict, region_id,
                      leader=None):
        """ONE definition of the forward rung for both ladder entry points:
        returns ``(served, resp)`` — ``served`` is the remote's final answer
        (the ladder ends there), else None with ``resp`` possibly replaced
        by the remote's region-error response (leadership moved again, or
        its watermark lags: its hints are fresher than ours — degrade from
        it, never hop again)."""
        leader = leader or self._leader_of(region_id)
        if leader is None or leader == self.store_id:
            _count_forward("no_leader")
            return None, resp
        fresp = self._forward(leader, method, req)
        if fresp is None:
            return None, resp
        ferr = fresp.get("error") if isinstance(fresp, dict) else None
        if not (isinstance(ferr, dict)
                and ({"not_leader", "data_not_ready"} & ferr.keys())):
            _count_forward("ok")
            return fresp, resp
        _count_forward("remote_region_error")
        return None, fresp

    def _forward(self, leader: int, method: str, req: dict):
        """The one-hop forward.  Returns the remote response, or None when
        the hop could not complete (breaker open, no route, connection
        failure, timeout) — each counted under its own outcome."""
        if not self._allow(leader):
            _count_forward("breaker_open")
            return None
        fctx = dict(req.get("context") or {})
        fctx["forwarded"] = True
        freq = dict(req)
        freq["context"] = fctx
        with trace.span("ladder.forward", target_store=leader,
                        store=self.store_id or "") as sp:
            # the hop rides the SAME trace (docs/tracing.md): the leader's
            # RPC span parents onto this forward span (the current span)
            trace.inject(fctx)
            try:
                r = self.call(leader, method, freq)
            except TimeoutError:
                self._record_failure(leader)
                _count_forward("timeout")
                sp.tag(outcome="timeout")
                return None
            except Exception:  # noqa: BLE001 — no route / conn refused / reset
                self._record_failure(leader)
                _count_forward("error")
                sp.tag(outcome="error")
                return None
            self._record_success(leader)
            sp.tag(outcome="ok")
            return r

    def _stale_fallback(self, method: str, req: dict, resp: dict, local,
                        region_id, cause: str):
        """The third rung: serve locally as a follower stale read iff the
        request permits (``stale_read``/``stale_fallback`` + a read_ts the
        engine admits against the RegionReadProgress pair)."""
        ctx = req.get("context") or {}
        permit = bool(ctx.get("stale_read") or ctx.get("stale_fallback"))
        read_ts = ctx.get("read_ts")
        # the snapshot ts the request already reads at: an MVCC read at
        # ts V served off a replica whose watermark covers V is
        # byte-identical to the leader's answer — "staleness" is only
        # in which V the CLIENT chose.  A declared read_ts BELOW that V
        # is clamped up (same as copr's stale_read_ctx / storage's
        # _stale_snap_ctx): admission must cover the ts the MVCC pass
        # actually reads at, or a lagging replica silently misses
        # committed data
        mvcc_ts = req.get("version") if "version" in req else req.get("start_ts")
        if mvcc_ts is not None and (read_ts is None or int(read_ts) < int(mvcc_ts)):
            read_ts = mvcc_ts
        if not permit or read_ts is None:
            return self._refuse(resp, region_id, "no_permit")
        sctx = dict(ctx)
        sctx["stale_read"] = True
        sctx["read_ts"] = int(read_ts)
        sctx.pop("replica_read", None)
        sreq = dict(req)
        sreq["context"] = sctx
        with trace.span("ladder.stale_serve", cause=cause,
                        store=self.store_id or "") as sp:
            r = local(sreq)
            rerr = r.get("error") if isinstance(r, dict) else None
            if not rerr:
                _count_stale_serve(_path_of(method), cause)
                sp.tag(outcome="served")
                return r
            sp.tag(outcome="refused")
        if isinstance(rerr, dict) and "data_not_ready" in rerr:
            return self._refuse(r, region_id, "data_not_ready")
        return self._refuse(resp, region_id, "stale_failed")

    # -- refusal (typed, hint-carrying) --------------------------------------

    def _refuse(self, resp: dict, region_id, cause: str) -> dict:
        """Enrich the region error with everything the client needs to act:
        the freshest leader hint, this store's ``safe_ts`` floor, and the
        region's progress pair."""
        _count_refuse(cause)
        cur = trace.current()
        if cur is not None:
            # refusal leaves a mark on the trace even though no rung served
            cur.tag(ladder_refused=cause)
        err = resp.get("error") if isinstance(resp, dict) else None
        if not isinstance(err, dict):
            return resp
        hints: dict = {}
        if self.resolved_ts is not None:
            hints["safe_ts"] = self.resolved_ts.safe_ts()
            if region_id is not None:
                resolved, required = self.resolved_ts.progress_of(region_id)
                hints["resolved_ts"] = resolved
                hints["required_apply_index"] = required
        leader = self._leader_of(region_id)
        for key in ("not_leader", "data_not_ready"):
            sub = err.get(key)
            if isinstance(sub, dict):
                for k, v in hints.items():
                    sub.setdefault(k, v)
                if sub.get("leader_store") is None and leader is not None:
                    sub["leader_store"] = leader
        return resp

    def _leader_of(self, region_id) -> int | None:
        if self.store is None or region_id is None:
            return None
        return self.store.leader_store_of(region_id)
