#!/usr/bin/env python
"""Raft write-path micro-bench: inline apply vs the apply pipeline.

Measures proposals/sec through a single-store raft group over the durable
native engine (WAL fsync per append — the reference's
tests/benches/hierarchy/ engine→raft write costs).  Two configurations:

  inline    — append + apply serialized on the raft thread (round-1 shape)
  pipeline  — append on the raft thread, apply on workers (batch-system
              shape, apply.rs): fsync of entry N+1 overlaps apply of N

Prints one JSON line with both rates.  BENCH_RAFT_N controls ops (default
2000), BENCH_RAFT_BATCH the concurrent in-flight proposals (default 64).
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.raftkv import RaftKv
from tikv_tpu.raft.store import ChannelTransport
from tikv_tpu.server.node import FIRST_REGION_ID, Node
from tikv_tpu.storage.engine import WriteBatch


def run_config(pipelined: bool, n_ops: int, batch: int, raft_log: bool = False) -> float:
    from tikv_tpu.native.engine import NativeEngine, native_available

    tmp = tempfile.mkdtemp()
    engine = NativeEngine(path=f"{tmp}/db") if native_available() else None
    rl = None
    if raft_log:
        from tikv_tpu.native.raftlog import NativeRaftLog, raftlog_available

        if raftlog_available():
            rl = NativeRaftLog(f"{tmp}/raftlog")
    pd = MockPd()
    transport = ChannelTransport()
    node = Node(pd, transport, engine=engine, raft_log=rl)
    if rl is not None and engine is not None:
        # reference sync-log split: entries durable in the log engine,
        # apply writes buffered, kvdb flushed before purge (store.py)
        engine.set_sync(False)
        node.store.kv_buffered = True
    if not pipelined:
        node.store.stop_apply_pipeline()
    transport.register(node.store)
    node.try_bootstrap_cluster([node.store_id])
    node.create_region_peers()
    peer = node.store.peers[FIRST_REGION_ID]
    peer.node.campaign()
    node.pump()
    assert peer.node.is_leader()
    node.start(tick_interval=0.05)
    kv = RaftKv(node.store)
    ctx = {"region_id": FIRST_REGION_ID}

    # warmup
    wb = WriteBatch()
    wb.put_cf("default", b"warm", b"w")
    kv.write(ctx, wb)

    done = threading.Semaphore(0)
    inflight = threading.Semaphore(batch)
    errors = []

    def propose(i: int) -> None:
        wb = WriteBatch()
        wb.put_cf("default", b"bench-%08d" % i, b"v" * 64)
        cmd = {
            "epoch": (peer.region.epoch.conf_ver, peer.region.epoch.version),
            "ops": list(wb.ops),
        }

        def cb(r):
            if isinstance(r, Exception):
                errors.append(r)
            inflight.release()
            done.release()

        peer.propose_cmd(cmd, cb)

    t0 = time.perf_counter()
    for i in range(n_ops):
        inflight.acquire()
        propose(i)
    for _ in range(n_ops):
        done.acquire()
    dt = time.perf_counter() - t0
    assert not errors, errors[0]
    assert peer.apply_index >= n_ops, (peer.apply_index, n_ops)
    node.stop()
    close = getattr(node.store.engine, "close", None)
    if close:
        close()
    return n_ops / dt


def main() -> None:
    n = int(os.environ.get("BENCH_RAFT_N", "2000"))
    batch = int(os.environ.get("BENCH_RAFT_BATCH", "64"))
    from tikv_tpu.native.raftlog import raftlog_available

    inline = run_config(False, n, batch)
    pipe = run_config(True, n, batch)
    have_rlog = raftlog_available()
    # never attest the raftlog configuration when it silently fell back
    rlog = run_config(True, n, batch, raft_log=True) if have_rlog else pipe
    print(
        json.dumps(
            {
                "metric": "raft_write_path_proposals_per_sec"
                + ("" if have_rlog else "_no_raftlog"),
                "value": round(rlog, 1),
                "unit": "proposals/sec",
                "inline_per_sec": round(inline, 1),
                "pipeline_per_sec": round(pipe, 1),
                "raftlog_per_sec": round(rlog, 1),
                "pipeline_speedup": round(pipe / inline, 3),
                "raftlog_speedup_vs_pipeline": round(rlog / pipe, 3),
                "ops": n,
                "inflight": batch,
            }
        )
    )


if __name__ == "__main__":
    main()
